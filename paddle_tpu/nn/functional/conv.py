"""Convolutions (reference: python/paddle/nn/functional/conv.py; kernels
paddle/phi/kernels/gpudnn/conv_* -> cuDNN). Here: lax.conv_general_dilated,
which XLA maps onto the MXU — the TPU path needs no vendor conv library."""
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _pair(v, n=2):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _padding(padding, spatial):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * spatial
    padding = list(padding)
    if len(padding) == spatial:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * spatial:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(spatial)]
    raise ValueError(f"bad padding {padding}")


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, spatial,
             data_format, name):
    strides = _pair(stride, spatial)
    dilations = _pair(dilation, spatial)
    pad = _padding(padding, spatial)
    if spatial == 1:
        dn_str = ("NCH", "OIH", "NCH") if data_format in ("NCL", "NCH") else ("NHC", "OIH", "NHC")
    elif spatial == 2:
        dn_str = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC")
    else:
        dn_str = ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" else ("NDHWC", "OIDHW", "NDHWC")

    def impl(a, w, *maybe_b):
        if a.dtype != w.dtype:
            # promote like matmul does — lax.conv requires equal dtypes
            # (mixed fp32 activations / bf16 weights is the common amp case)
            ct = jnp.result_type(a.dtype, w.dtype)
            a, w = a.astype(ct), w.astype(ct)
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, dn_str)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=jnp.float32 if a.dtype == jnp.float32 else None)
        out = out.astype(a.dtype)
        if maybe_b:
            b = maybe_b[0]
            if data_format.startswith("NC"):
                out = out + b.reshape((1, -1) + (1,) * spatial)
            else:
                out = out + b
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply_op(name, impl, args, {})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    data_format, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    data_format, "conv3d")


def _channels_last_transpose(fn, x, n, kwargs):
    """Run a channels-first conv_transpose on channels-last data via a
    transpose pair (XLA folds the layout changes into the convolution)."""
    to_cf = (0, n + 1) + tuple(range(1, n + 1))
    to_cl = (0,) + tuple(range(2, n + 2)) + (1,)
    out = fn(x.transpose(to_cf), **kwargs)
    return out.transpose(to_cl)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW"):
    """Transposed conv. paddle weight layout: [in, out//groups, kh, kw]."""
    if data_format == "NHWC":
        return _channels_last_transpose(
            conv2d_transpose, x, 2,
            dict(weight=weight, bias=bias, stride=stride, padding=padding,
                 output_padding=output_padding, dilation=dilation,
                 groups=groups, output_size=output_size,
                 data_format="NCHW"))
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 2,
                              "conv2d_transpose", output_size=output_size)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, name, output_size=None):
    """Generic transposed conv over n spatial dims: conv_general_dilated
    with lhs_dilation. output_size (when given) resolves the stride
    ambiguity by deriving the extra high-side padding, with validation."""
    strides = _pair(stride, n)
    dilations = _pair(dilation, n)
    opad = list(_pair(output_padding, n))
    if isinstance(padding, str):
        # reference string semantics for transposed conv: VALID = no pad;
        # SAME = output exactly input*stride (pad split low/high, shortfall
        # made up with output_padding)
        mode = padding.upper()
        w_arr = weight.data if hasattr(weight, "data") else weight
        pads = []
        for i in range(n):
            if mode == "VALID":
                pads.append((0, 0))
                continue
            total = dilations[i] * (w_arr.shape[2 + i] - 1) + 1 - strides[i]
            if total < 0:
                opad[i] += -total
                total = 0
            pads.append((total // 2, total - total // 2))
    else:
        pads = _padding(padding, n)
    opad = tuple(opad)
    if output_size is not None:
        x_arr = x.data if hasattr(x, "data") else x
        w_arr = weight.data if hasattr(weight, "data") else weight
        osz = _pair(output_size, n)
        opad = tuple(
            osz[i] - ((x_arr.shape[2 + i] - 1) * strides[i]
                      - pads[i][0] - pads[i][1]
                      + dilations[i] * (w_arr.shape[2 + i] - 1) + 1)
            for i in range(n))
        if any(p < 0 or p >= strides[i] for i, p in enumerate(opad)):
            raise ValueError(
                f"output_size {list(osz)} not reachable with "
                f"stride {strides}")
    spatial = "DHW"[3 - n:]
    fmt = "NC" + spatial
    wfmt = "OI" + spatial

    def impl(a, w, *maybe_b):
        ks = w.shape[2:]
        axes = tuple(range(2, 2 + n))
        if groups > 1:
            ci = a.shape[1]
            w_g = w.reshape((groups, ci // groups, w.shape[1]) + ks)
            w_g = jnp.flip(w_g, axis=tuple(range(3, 3 + n)))
            w_t = jnp.swapaxes(w_g, 1, 2).reshape(
                (groups * w.shape[1], ci // groups) + ks)
        else:
            w_t = jnp.swapaxes(jnp.flip(w, axis=axes), 0, 1)
        pad_pairs = [
            (dilations[i] * (ks[i] - 1) - pads[i][0],
             dilations[i] * (ks[i] - 1) - pads[i][1] + opad[i])
            for i in range(n)]
        dn = jax.lax.conv_dimension_numbers(a.shape, w_t.shape,
                                            (fmt, wfmt, fmt))
        out = jax.lax.conv_general_dilated(
            a, w_t, window_strides=(1,) * n, padding=pad_pairs,
            lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn, feature_group_count=groups)
        if maybe_b:
            out = out + maybe_b[0].reshape((1, -1) + (1,) * n)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply_op(name, impl, args, {})


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCL"):
    if data_format == "NLC":
        return _channels_last_transpose(
            conv1d_transpose, x, 1,
            dict(weight=weight, bias=bias, stride=stride, padding=padding,
                 output_padding=output_padding, dilation=dilation,
                 groups=groups, output_size=output_size, data_format="NCL"))
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 1,
                              "conv1d_transpose", output_size=output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCDHW"):
    if data_format == "NDHWC":
        return _channels_last_transpose(
            conv3d_transpose, x, 3,
            dict(weight=weight, bias=bias, stride=stride, padding=padding,
                 output_padding=output_padding, dilation=dilation,
                 groups=groups, output_size=output_size,
                 data_format="NCDHW"))
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 3,
                              "conv3d_transpose", output_size=output_size)
