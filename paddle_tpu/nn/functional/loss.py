"""Loss functionals (reference: python/paddle/nn/functional/loss.py; kernels
cross_entropy / softmax_with_cross_entropy etc.)."""
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    """paddle.nn.functional.cross_entropy (reference:
    python/paddle/nn/functional/loss.py cross_entropy): input is logits by
    default (use_softmax=True), label is int class ids or soft distribution."""
    def impl(logits, lbl, *maybe_w):
        last = axis in (-1, logits.ndim - 1)
        if use_softmax and not soft_label and last and not maybe_w:
            # streamed lse path: never materializes the [N, V] fp32
            # log-softmax (2GB at 16k x 32k) — fp32 accumulation happens
            # inside the fused reduction; bwd is softmax - onehot
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logits.ndim:
                lbl_i = jnp.squeeze(lbl_i, axis=-1)
            valid = (lbl_i != ignore_index)
            safe = jnp.where(valid, lbl_i, 0)
            m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
            shifted = (logits - m).astype(jnp.float32)
            lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) \
                + m[..., 0].astype(jnp.float32)
            picked = jnp.take_along_axis(
                logits, safe[..., None], axis=-1)[..., 0].astype(jnp.float32)
            loss = lse - picked
            if label_smoothing > 0:
                mean_l = jnp.mean(logits.astype(jnp.float32), axis=-1)
                loss = (1 - label_smoothing) * loss \
                    + label_smoothing * (lse - mean_l)
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(valid.astype(jnp.float32)), 1.0)
            return _reduce(loss, reduction)
        if use_softmax:
            # fp32 softmax accumulation regardless of logits dtype
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            tgt = lbl
            if label_smoothing > 0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_classes
            loss = -jnp.sum(tgt * logp, axis=axis)
            valid = jnp.ones(loss.shape, dtype=logp.dtype)
        else:
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logits.ndim:  # [N, 1] style labels
                lbl_i = jnp.squeeze(lbl_i, axis=axis)
            valid = (lbl_i != ignore_index)
            safe = jnp.where(valid, lbl_i, 0)
            picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0] \
                if axis in (-1, logits.ndim - 1) else \
                jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
            if label_smoothing > 0:
                smooth = -jnp.mean(logp, axis=axis)
                loss = (1 - label_smoothing) * (-picked) + label_smoothing * smooth
            else:
                loss = -picked
            if maybe_w:
                w = maybe_w[0]
                loss = loss * jnp.take(w, safe)
            loss = jnp.where(valid, loss, 0.0)
            valid = valid.astype(logp.dtype)
        if reduction == "mean":
            if maybe_w and not soft_label:
                w = maybe_w[0]
                lbl_i = lbl.astype(jnp.int32)
                if lbl_i.ndim == logits.ndim:
                    lbl_i = jnp.squeeze(lbl_i, axis=axis)
                safe = jnp.where(valid > 0, lbl_i, 0)
                denom = jnp.sum(jnp.take(w, safe) * valid)
            else:
                denom = jnp.maximum(jnp.sum(valid), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("cross_entropy", impl, args, {})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    def impl(logp, lbl, *maybe_w):
        lbl_i = lbl.astype(jnp.int32)
        valid = (lbl_i != ignore_index)
        safe = jnp.where(valid, lbl_i, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        loss = -picked
        if maybe_w:
            loss = loss * jnp.take(maybe_w[0], safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.take(maybe_w[0], safe) * valid if maybe_w else valid
            return jnp.sum(loss) / jnp.maximum(jnp.sum(denom.astype(logp.dtype)), 1e-12)
        return _reduce(loss, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("nll_loss", impl, args, {})


def mse_loss(input, label, reduction="mean"):
    def impl(a, b):
        return _reduce((a - b) ** 2, reduction)
    return apply_op("mse_loss", impl, (input, label), {})


def l1_loss(input, label, reduction="mean"):
    def impl(a, b):
        return _reduce(jnp.abs(a - b), reduction)
    return apply_op("l1_loss", impl, (input, label), {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def impl(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d / delta, abs_d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", impl, (input, label), {})


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def impl(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d <= delta, 0.5 * d * d,
                         delta * (abs_d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply_op("huber_loss", impl, (input, label), {})


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    def impl(p, y, *maybe_w):
        p_ = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p_) + (1 - y) * jnp.log1p(-p_))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("binary_cross_entropy", impl, args, {})


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    def impl(z, y, *rest):
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        i = 0
        if pos_weight is not None:
            pw = rest[i]; i += 1
            loss = loss * (y * (pw - 1) + 1)
        if weight is not None:
            loss = loss * rest[i]
        return _reduce(loss, reduction)
    args = [logit, label]
    if pos_weight is not None:
        args.append(pos_weight)
    if weight is not None:
        args.append(weight)
    return apply_op("bce_with_logits", impl, tuple(args), {})


def kl_div(input, label, reduction="mean", log_target=False):
    def impl(logp, tgt):
        if log_target:
            loss = jnp.exp(tgt) * (tgt - logp)
        else:
            t = jnp.maximum(tgt, 0)
            loss = jnp.where(tgt > 0, tgt * (jnp.log(jnp.maximum(tgt, 1e-30)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", impl, (input, label), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    def impl(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply_op("margin_ranking_loss", impl, (input, other, label), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    def impl(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply_op("hinge_embedding_loss", impl, (input, label), {})


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def impl(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", impl, (input1, input2, label), {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def impl(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce(loss, reduction)
    return apply_op("triplet_margin_loss", impl, (input, positive, negative), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    def impl(z, y, *maybe_n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if maybe_n:
            loss = loss / maybe_n[0]
        return _reduce(loss, reduction)
    args = (logit, label) if normalizer is None else (logit, label, normalizer)
    return apply_op("sigmoid_focal_loss", impl, args, {})


def log_loss(input, label, epsilon=1e-4):
    def impl(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply_op("log_loss", impl, (input, label), {})


def square_error_cost(input, label):
    def impl(a, b):
        return (a - b) ** 2
    return apply_op("square_error_cost", impl, (input, label), {})


def dice_loss(input, label, epsilon=1e-5):
    """1 - 2*|X∩Y| / (|X|+|Y|) over the last (class-prob) dim (reference
    dice_loss)."""
    def impl(p, y):
        yoh = jax.nn.one_hot(jnp.squeeze(y, -1), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * yoh, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(yoh, axis=red)
        return jnp.mean(1.0 - 2.0 * inter / (union + epsilon))
    return apply_op("dice_loss", impl, (input, label), {})


def soft_margin_loss(input, label, reduction="mean"):
    def impl(z, y):
        return _reduce(jnp.log1p(jnp.exp(-y.astype(z.dtype) * z)), reduction)
    return apply_op("soft_margin_loss", impl, (input, label), {})


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean"):
    def impl(z, y, *w):
        loss = -(y * jax.nn.log_sigmoid(z)
                 + (1 - y) * jax.nn.log_sigmoid(-z))
        if w:
            loss = loss * w[0]
        return _reduce(loss.mean(-1), reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("multi_label_soft_margin_loss", impl, args, {})


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    def impl(z, y, *w):
        n, c = z.shape
        gold = jnp.take_along_axis(z, y[:, None], axis=1)
        m = jnp.maximum(margin - gold + z, 0.0) ** p
        if w:
            m = m * w[0][y][:, None]
        m = m.at[jnp.arange(n), y].set(0.0)
        return _reduce(m.sum(-1) / c, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("multi_margin_loss", impl, args, {})


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    def impl(z, y):
        if log_input:
            loss = jnp.exp(z) - y * z
        else:
            loss = z - y * jnp.log(z + epsilon)
        if full:
            stirling = y * jnp.log(y + (y <= 1)) - y + \
                0.5 * jnp.log(2 * jnp.pi * jnp.maximum(y, 1.0))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply_op("poisson_nll_loss", impl, (input, label), {})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    def impl(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)
    return apply_op("gaussian_nll_loss", impl, (input, label, variance), {})


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    d_ap = distance_function(input, positive)
    d_an = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        from ... import ops as _ops
        d_an = _ops.minimum(d_an, d_pn)
    from ... import ops as _ops
    loss = (d_ap - d_an + margin).clip(min=0.0)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False):
    """Hierarchical sigmoid over the default complete binary tree (reference
    hsigmoid_loss / phi hsigmoid kernels). Each class's root-to-leaf path is
    decoded from its index; loss = -sum log sigmoid(code * (w·x + b))."""
    import numpy as np

    def impl(x, y, w, *rest):
        b = rest[0] if rest else None
        if path_table is not None:
            raise NotImplementedError(
                "custom-tree hsigmoid: pass dense path tensors instead")
        n_inner = int(num_classes) - 1  # inner nodes of a complete tree
        depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
        # leaf id -> path of inner-node ids + left/right codes, computed by
        # walking the class index through the heap layout (host-side ints)
        codes = ((y[..., None] + n_inner + 1) //
                 (2 ** jnp.arange(depth, 0, -1))) - 1   # ancestor heap ids
        valid = codes >= 0
        node = jnp.clip(codes, 0, n_inner - 1)
        child = ((y[..., None] + n_inner + 1) //
                 (2 ** (jnp.arange(depth, 0, -1) - 1)))
        sign = jnp.where(child % 2 == 0, 1.0, -1.0)  # left child => code +1
        logits = jnp.einsum("bd,bpd->bp", x, w[node])
        if b is not None:
            logits = logits + jnp.squeeze(b, -1)[node]
        loss = -jax.nn.log_sigmoid(sign * logits)
        return jnp.sum(jnp.where(valid, loss, 0.0), axis=-1, keepdims=True).mean()
    args = (input, label, weight) if bias is None else (input, label, weight, bias)
    return apply_op("hsigmoid_loss", impl, args, {})


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-class margin softmax (reference margin_cross_entropy op:
    cos(m1*theta + m2) - m3 on the gold logit, then scaled CE). The model-
    parallel variant shards classes over `group`'s mp axis via GSPMD instead
    of the reference's c_softmax allreduce pair."""
    def impl(z, y):
        theta = jnp.arccos(jnp.clip(z, -1.0 + 1e-7, 1.0 - 1e-7))
        gold = jnp.cos(margin1 * theta + margin2) - margin3
        yoh = jax.nn.one_hot(y, z.shape[-1], dtype=z.dtype)
        adj = jnp.where(yoh > 0, gold, z) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.sum(yoh * logp, axis=-1, keepdims=True)
        if reduction == "mean":
            loss = loss.mean()
        elif reduction == "sum":
            loss = loss.sum()
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss
    return apply_op("margin_cross_entropy", impl, (logits, label), {})


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """RNN-Transducer loss via the log-space alpha recursion (Graves 2012),
    scanned over T (reference rnnt_loss wraps warprnnt; here the DP is XLA
    lax.scan — TPU-friendly, batched).

    input: [B, T, U+1, V] log-probs (pre log_softmax accepted), label [B, U].
    """
    def impl(logits, y, t_len, u_len):
        logp = jax.nn.log_softmax(logits, axis=-1)
        b, tmax, up1, v = logp.shape
        umax = up1 - 1
        blank_lp = logp[..., blank]                       # [B, T, U+1]
        ylp = jnp.take_along_axis(
            logp[:, :, :umax, :],
            jnp.broadcast_to(y[:, None, :, None], (b, tmax, umax, 1)),
            axis=-1)[..., 0]                              # [B, T, U]
        if fastemit_lambda:
            # FastEmit (arXiv:2010.11148): scale the gradient flowing through
            # the label-emission path by (1 + lambda) without changing the
            # loss value — same effect as the reference kernel's in-gradient
            # scaling (warprnnt fastemit_lambda).
            lam = jnp.asarray(fastemit_lambda, ylp.dtype)
            ylp = (1.0 + lam) * ylp - jax.lax.stop_gradient(lam * ylp)
        neg_inf = jnp.float32(-1e30)

        def t_step(alpha_prev, xs):
            blank_t, y_t, t = xs                          # [B,U+1], [B,U]
            # alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
            #                         alpha[t, u-1] + y[t, u-1])
            from_left = alpha_prev + blank_t              # emit blank: t-1 -> t
            def u_step(carry, xs_u):
                fl, yl = xs_u                             # [B], [B]
                val = jnp.logaddexp(fl, carry + yl)
                return val, val
            first = from_left[:, 0]
            _, rest = jax.lax.scan(
                u_step, first,
                (from_left[:, 1:].T, y_t.T))
            alpha_t = jnp.concatenate([first[:, None], rest.T], axis=1)
            return alpha_t, alpha_t

        # alpha[0, u] = cumsum of label emissions at t=0
        alpha0 = jnp.concatenate(
            [jnp.zeros((b, 1), jnp.float32),
             jnp.cumsum(ylp[:, 0, :], axis=-1)], axis=1)
        ts = jnp.arange(1, tmax)
        _, alphas = jax.lax.scan(
            t_step, alpha0,
            (blank_lp[:, :-1].transpose(1, 0, 2)[: tmax - 1],
             ylp.transpose(1, 0, 2)[1:tmax] if tmax > 1 else
             jnp.zeros((0, b, umax)), ts))
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U+1]
        # final: alpha[t_len-1, u_len] + blank[t_len-1, u_len]
        tl = jnp.clip(t_len - 1, 0, tmax - 1)
        ul = jnp.clip(u_len, 0, umax)
        a_fin = alphas[tl, jnp.arange(b), ul]
        lp_fin = blank_lp[jnp.arange(b), tl, ul]
        nll = -(a_fin + lp_fin)
        if reduction == "mean":
            return nll.mean()
        if reduction == "sum":
            return nll.sum()
        return nll
    return apply_op("rnnt_loss", impl,
                    (input, label, input_lengths, label_lengths), {})


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None):
    """Adaptive softmax (Grave et al. 2017; reference
    adaptive_log_softmax_with_loss): head = shortlist + one logit per tail
    cluster; each tail cluster projects down then predicts within-cluster.
    Returns (output=per-sample log-prob of the gold class, loss=-mean)."""
    def impl(x, y, hw, *rest):
        if head_bias is not None:
            hb, tails = rest[0], rest[1:]
        else:
            hb, tails = None, rest
        n_clusters = len(cutoffs)
        shortlist = cutoffs[0] if n_clusters else hw.shape[1]
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lp = jax.nn.log_softmax(head_logits, axis=-1)
        # gold in shortlist: direct lookup (clamped gather, masked later)
        out = jnp.take_along_axis(
            head_lp, jnp.clip(y, 0, shortlist - 1)[:, None], axis=1)[:, 0]
        lo = shortlist
        for ci in range(len(tails) // 2):
            proj, w = tails[2 * ci], tails[2 * ci + 1]
            hi = cutoffs[ci + 1] if ci + 1 < len(cutoffs) else lo + w.shape[1]
            cluster_lp = jax.nn.log_softmax((x @ proj) @ w, axis=-1)
            in_c = (y >= lo) & (y < hi)
            rel = jnp.clip(y - lo, 0, w.shape[1] - 1)
            val = head_lp[:, shortlist + ci] + \
                jnp.take_along_axis(cluster_lp, rel[:, None], axis=1)[:, 0]
            out = jnp.where(in_c, val, out)
            lo = hi
        return out, -out.mean()
    tails_flat = [t for pair in tail_weights for t in pair]
    args = (input, label, head_weight) + \
        ((head_bias,) if head_bias is not None else ()) + tuple(tails_flat)
    return apply_op("adaptive_log_softmax_with_loss", impl, args, {})
