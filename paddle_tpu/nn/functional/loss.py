"""Loss functionals (reference: python/paddle/nn/functional/loss.py; kernels
cross_entropy / softmax_with_cross_entropy etc.)."""
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    """paddle.nn.functional.cross_entropy (reference:
    python/paddle/nn/functional/loss.py cross_entropy): input is logits by
    default (use_softmax=True), label is int class ids or soft distribution."""
    def impl(logits, lbl, *maybe_w):
        last = axis in (-1, logits.ndim - 1)
        if use_softmax and not soft_label and last and not maybe_w:
            # streamed lse path: never materializes the [N, V] fp32
            # log-softmax (2GB at 16k x 32k) — fp32 accumulation happens
            # inside the fused reduction; bwd is softmax - onehot
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logits.ndim:
                lbl_i = jnp.squeeze(lbl_i, axis=-1)
            valid = (lbl_i != ignore_index)
            safe = jnp.where(valid, lbl_i, 0)
            m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
            shifted = (logits - m).astype(jnp.float32)
            lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) \
                + m[..., 0].astype(jnp.float32)
            picked = jnp.take_along_axis(
                logits, safe[..., None], axis=-1)[..., 0].astype(jnp.float32)
            loss = lse - picked
            if label_smoothing > 0:
                mean_l = jnp.mean(logits.astype(jnp.float32), axis=-1)
                loss = (1 - label_smoothing) * loss \
                    + label_smoothing * (lse - mean_l)
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(valid.astype(jnp.float32)), 1.0)
            return _reduce(loss, reduction)
        if use_softmax:
            # fp32 softmax accumulation regardless of logits dtype
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            tgt = lbl
            if label_smoothing > 0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_classes
            loss = -jnp.sum(tgt * logp, axis=axis)
            valid = jnp.ones(loss.shape, dtype=logp.dtype)
        else:
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logits.ndim:  # [N, 1] style labels
                lbl_i = jnp.squeeze(lbl_i, axis=axis)
            valid = (lbl_i != ignore_index)
            safe = jnp.where(valid, lbl_i, 0)
            picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0] \
                if axis in (-1, logits.ndim - 1) else \
                jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
            if label_smoothing > 0:
                smooth = -jnp.mean(logp, axis=axis)
                loss = (1 - label_smoothing) * (-picked) + label_smoothing * smooth
            else:
                loss = -picked
            if maybe_w:
                w = maybe_w[0]
                loss = loss * jnp.take(w, safe)
            loss = jnp.where(valid, loss, 0.0)
            valid = valid.astype(logp.dtype)
        if reduction == "mean":
            if maybe_w and not soft_label:
                w = maybe_w[0]
                lbl_i = lbl.astype(jnp.int32)
                if lbl_i.ndim == logits.ndim:
                    lbl_i = jnp.squeeze(lbl_i, axis=axis)
                safe = jnp.where(valid > 0, lbl_i, 0)
                denom = jnp.sum(jnp.take(w, safe) * valid)
            else:
                denom = jnp.maximum(jnp.sum(valid), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("cross_entropy", impl, args, {})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    def impl(logp, lbl, *maybe_w):
        lbl_i = lbl.astype(jnp.int32)
        valid = (lbl_i != ignore_index)
        safe = jnp.where(valid, lbl_i, 0)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        loss = -picked
        if maybe_w:
            loss = loss * jnp.take(maybe_w[0], safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.take(maybe_w[0], safe) * valid if maybe_w else valid
            return jnp.sum(loss) / jnp.maximum(jnp.sum(denom.astype(logp.dtype)), 1e-12)
        return _reduce(loss, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("nll_loss", impl, args, {})


def mse_loss(input, label, reduction="mean"):
    def impl(a, b):
        return _reduce((a - b) ** 2, reduction)
    return apply_op("mse_loss", impl, (input, label), {})


def l1_loss(input, label, reduction="mean"):
    def impl(a, b):
        return _reduce(jnp.abs(a - b), reduction)
    return apply_op("l1_loss", impl, (input, label), {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def impl(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d / delta, abs_d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", impl, (input, label), {})


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def impl(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d <= delta, 0.5 * d * d,
                         delta * (abs_d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply_op("huber_loss", impl, (input, label), {})


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    def impl(p, y, *maybe_w):
        p_ = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p_) + (1 - y) * jnp.log1p(-p_))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply_op("binary_cross_entropy", impl, args, {})


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    def impl(z, y, *rest):
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        i = 0
        if pos_weight is not None:
            pw = rest[i]; i += 1
            loss = loss * (y * (pw - 1) + 1)
        if weight is not None:
            loss = loss * rest[i]
        return _reduce(loss, reduction)
    args = [logit, label]
    if pos_weight is not None:
        args.append(pos_weight)
    if weight is not None:
        args.append(weight)
    return apply_op("bce_with_logits", impl, tuple(args), {})


def kl_div(input, label, reduction="mean", log_target=False):
    def impl(logp, tgt):
        if log_target:
            loss = jnp.exp(tgt) * (tgt - logp)
        else:
            t = jnp.maximum(tgt, 0)
            loss = jnp.where(tgt > 0, tgt * (jnp.log(jnp.maximum(tgt, 1e-30)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", impl, (input, label), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    def impl(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply_op("margin_ranking_loss", impl, (input, other, label), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    def impl(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply_op("hinge_embedding_loss", impl, (input, label), {})


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def impl(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", impl, (input1, input2, label), {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def impl(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce(loss, reduction)
    return apply_op("triplet_margin_loss", impl, (input, positive, negative), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    def impl(z, y, *maybe_n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if maybe_n:
            loss = loss / maybe_n[0]
        return _reduce(loss, reduction)
    args = (logit, label) if normalizer is None else (logit, label, normalizer)
    return apply_op("sigmoid_focal_loss", impl, args, {})


def log_loss(input, label, epsilon=1e-4):
    def impl(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply_op("log_loss", impl, (input, label), {})


def square_error_cost(input, label):
    def impl(a, b):
        return (a - b) ** 2
    return apply_op("square_error_cost", impl, (input, label), {})
