"""Functional-surface completion ops (reference: assorted
python/paddle/nn/functional/ modules — vision warps, CTC, sequence utils,
sampling-based activations)."""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from ...core import random as _random


def bilinear(x1, x2, weight, bias=None):
    """out[b, o] = x1[b] @ W[o] @ x2[b] (reference functional/common.py
    bilinear; W: [out, in1, in2])."""
    def impl(a, b, w, *mb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b,
                         preferred_element_type=jnp.float32).astype(a.dtype)
        if mb:
            out = out + mb[0]
        return out

    args = (x1, x2, weight) if bias is None else (x1, x2, weight, bias)
    return apply_op("bilinear", impl, args, {})


def pdist(x, p=2.0):
    """Condensed pairwise distance vector (reference functional/distance.py
    pdist): upper-triangle of cdist(x, x) — one distance kernel, reused."""
    from ...ops.impl.linalg import cdist as _cdist_impl

    def impl(a):
        m = _cdist_impl(a, a, p=p, compute_mode="donot_use_mm")
        iu, ju = jnp.triu_indices(a.shape[0], k=1)
        return m[iu, ju]

    return apply_op("pdist", impl, (x,), {})


def feature_alpha_dropout(x, p=0.5, training=True):
    """Alpha dropout over whole channel maps (reference alpha_dropout
    family): keeps SELU self-normalizing statistics."""
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def impl(a):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(_random.next_key(), 1.0 - p, shape)
        q = 1.0 - p
        an = 1.0 / math.sqrt(q + alpha_p ** 2 * q * p)
        bn = -an * p * alpha_p
        return (jnp.where(keep, a, alpha_p) * an + bn).astype(a.dtype)

    return apply_op("feature_alpha_dropout", impl, (x,), {})


def channel_shuffle(x, groups, data_format="NCHW"):
    """Reference functional/vision.py channel_shuffle."""
    def impl(a):
        if data_format == "NCHW":
            b, c, h, w = a.shape
            return a.reshape(b, groups, c // groups, h, w).swapaxes(
                1, 2).reshape(b, c, h, w)
        b, h, w, c = a.shape
        return a.reshape(b, h, w, groups, c // groups).swapaxes(
            3, 4).reshape(b, h, w, c)

    return apply_op("channel_shuffle", impl, (x,), {})


def affine_grid(theta, out_shape, align_corners=True):
    """2D affine sampling grid [N, H, W, 2] (reference functional/vision.py
    affine_grid; theta [N, 2, 3])."""
    n, c, h, w = [int(s) for s in out_shape]

    def impl(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
        return jnp.einsum("nij,hwj->nhwi", th, base).astype(th.dtype)

    return apply_op("affine_grid", impl, (theta,), {})


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Sample NCHW input at normalized grid coords [N, H', W', 2]
    (reference functional/vision.py grid_sample; kernel
    grid_sample_kernel.cu). Gather-based bilinear/nearest."""
    def impl(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(img, yy, xx):
            # img [C,H,W]; yy/xx [H',W'] float
            if mode == "nearest":
                yi = jnp.clip(jnp.round(yy), 0, h - 1).astype(jnp.int32)
                xi = jnp.clip(jnp.round(xx), 0, w - 1).astype(jnp.int32)
                out = img[:, yi, xi]
                if padding_mode == "zeros":
                    inb = (yy >= -0.5) & (yy <= h - 0.5) & \
                        (xx >= -0.5) & (xx <= w - 0.5)
                    out = out * inb[None]
                return out
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy1, wx1 = yy - y0, xx - x0

            def tap(yi, xi, wgt):
                if padding_mode == "border":
                    yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                    xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                    return img[:, yc, xc] * wgt[None]
                inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                return img[:, yc, xc] * (wgt * inb)[None]

            return (tap(y0, x0, (1 - wy1) * (1 - wx1))
                    + tap(y0, x0 + 1, (1 - wy1) * wx1)
                    + tap(y0 + 1, x0, wy1 * (1 - wx1))
                    + tap(y0 + 1, x0 + 1, wy1 * wx1))

        return jax.vmap(sample)(a, fy, fx)

    return apply_op("grid_sample", impl, (x, grid), {})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im — inverse of unfold (reference functional/common.py fold).
    x: [N, C*kh*kw, L] -> [N, C, H, W] with overlapping patches summed."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)

    def impl(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        cols = a.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                ys = i * dh
                xs = j * dw
                out = out.at[:, :, ys:ys + nh * sh:sh,
                             xs:xs + nw * sw:sw].add(cols[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]

    return apply_op("fold", impl, (x,), {})


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """[..., maxlen] mask of positions < length (reference
    functional/sequence.py sequence_mask)."""
    from ...core.dtypes import convert_dtype
    dt = convert_dtype(dtype)

    def impl(l):
        m = maxlen
        if m is None:
            if isinstance(l, jax.core.Tracer):
                raise ValueError("sequence_mask under jit needs maxlen=")
            m = int(jnp.max(l))
        pos = jnp.arange(m)
        return (pos < l[..., None]).astype(dt)

    return apply_op("sequence_mask", impl, (lengths,), {},
                    differentiable=False)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """TSM temporal shift (reference functional/vision.py temporal_shift,
    kernel temporal_shift_kernel.cu): shift a channel slice one step
    forward/backward along the segment axis."""
    def impl(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate(
            [v[:, 1:, :fold_c], jnp.zeros_like(v[:, :1, :fold_c])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold_c:2 * fold_c]),
             v[:, :-1, fold_c:2 * fold_c]], axis=1)
        rest = v[:, :, 2 * fold_c:]
        out = jnp.concatenate([left, right, rest],
                              axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op("temporal_shift", impl, (x,), {})


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    """Reference functional/activation.py gumbel_softmax (straight-through
    when hard=True)."""
    def impl(a):
        g = jax.random.gumbel(_random.next_key(), a.shape, jnp.float32)
        y = jax.nn.softmax((a.astype(jnp.float32) + g) / temperature,
                           axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            onehot = jax.nn.one_hot(idx, y.shape[axis], axis=axis,
                                    dtype=y.dtype)
            # straight-through: forward one-hot, backward soft
            y = onehot - jax.lax.stop_gradient(y) + y
        return y.astype(a.dtype)

    return apply_op("gumbel_softmax", impl, (x,), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference functional/loss.py npair_loss."""
    def impl(an, po, lab):
        reg = l2_reg * ((an * an).sum(-1).mean()
                        + (po * po).sum(-1).mean()) * 0.25
        sim = an @ po.T
        same = (lab[:, None] == lab[None, :]).astype(jnp.float32)
        same = same / jnp.maximum(same.sum(-1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=-1)
        return reg + (-(same * logp).sum(-1)).mean()

    return apply_op("npair_loss", impl, (anchor, positive, labels), {})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist temporal classification loss (reference
    functional/loss.py ctc_loss over warpctc). Log-space alpha recursion as
    a lax.scan over time — XLA-native, static shapes.

    log_probs: [T, B, C] (paddle layout, logits accepted — log_softmax is
    applied); labels: [B, L] int; returns per-batch or reduced loss."""
    def impl(lp, lab, ilen, llen):
        t_max, b, c = lp.shape
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        l_max = lab.shape[1]
        s = 2 * l_max + 1
        # extended label sequence: blank, l1, blank, l2, ..., blank
        ext = jnp.full((b, s), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        NEG = -1e30

        # allowed skip transition: ext[s] != ext[s-2] (and ext[s] != blank)
        ext_prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)),
                            constant_values=blank)
        can_skip = (ext != blank) & (ext != ext_prev2)

        alpha0 = jnp.full((b, s), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(b), ext[:, 0]])
        has1 = l_max > 0
        if has1:
            alpha0 = alpha0.at[:, 1].set(lp[0, jnp.arange(b), ext[:, 1]])

        def step(alpha, lp_t):
            a1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)),
                         constant_values=NEG)
            a2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)),
                         constant_values=NEG)
            a2 = jnp.where(can_skip, a2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
            emit = jnp.take_along_axis(lp_t, ext, axis=-1)
            return merged + emit, merged + emit

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,S]

        # gather alpha at t = ilen-1, positions 2*llen and 2*llen-1
        tidx = jnp.clip(ilen - 1, 0, t_max - 1)
        at_end = alphas[tidx, jnp.arange(b)]          # [B, S]
        p_last = jnp.take_along_axis(
            at_end, jnp.clip(2 * llen, 0, s - 1)[:, None], axis=-1)[:, 0]
        p_prev = jnp.take_along_axis(
            at_end, jnp.clip(2 * llen - 1, 0, s - 1)[:, None],
            axis=-1)[:, 0]
        p_prev = jnp.where(llen > 0, p_prev, NEG)
        nll = -jnp.logaddexp(p_last, p_prev)
        if norm_by_times:
            nll = nll / jnp.maximum(ilen.astype(jnp.float32), 1.0)
        if reduction == "mean":
            return (nll / jnp.maximum(llen.astype(jnp.float32), 1.0)).mean()
        if reduction == "sum":
            return nll.sum()
        return nll

    return apply_op("ctc_loss", impl,
                    (log_probs, labels, input_lengths, label_lengths), {})


def gather_tree(ids, parents):
    """Beam-search ancestry backtrace (reference gather_tree op): walk from
    the last step back through parent pointers, emitting full sequences.
    ids/parents: [T, B, beam]. Reverse lax.scan — no host loop."""
    def impl(idv, par):
        t, b, k = idv.shape
        last_beams = jnp.broadcast_to(jnp.arange(k), (b, k))

        def back(beams, xs):
            step_ids, step_parents = xs
            tok = jnp.take_along_axis(step_ids, beams, axis=-1)
            prev = jnp.take_along_axis(step_parents, beams, axis=-1)
            return prev, tok

        _, toks = jax.lax.scan(back, last_beams, (idv, par), reverse=True)
        return toks
    return apply_op("gather_tree", impl, (ids, parents), {},
                    differentiable=False)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Partial-FC class-center sampling (reference class_center_sample op):
    keep all positive classes, pad with negative classes up to num_samples;
    returns (remapped_label, sampled_class_indices). Static-shape TPU
    design: the sampled set is always exactly num_samples long (padded with
    extra negatives), so downstream matmuls have fixed shapes."""
    import numpy as np
    from ...core import random as _rng

    def impl(y):
        flat = y.reshape(-1)
        pos = jnp.zeros((num_classes,), bool).at[flat].set(True)
        # rank classes: positives first (stable), then shuffled negatives
        noise = jax.random.uniform(_rng.next_key(), (num_classes,))
        keyv = jnp.where(pos, -1.0, noise)
        order = jnp.argsort(keyv)                    # positives lead
        sampled = order[:num_samples]
        # remap: class c -> its position in `sampled` (positives guaranteed in)
        inv = jnp.full((num_classes,), -1, jnp.int32)
        inv = inv.at[sampled].set(jnp.arange(num_samples, dtype=jnp.int32))
        return inv[flat].reshape(y.shape), sampled
    return apply_op("class_center_sample", impl, (label,), {},
                    differentiable=False)


def zeropad2d(x, padding, data_format="NCHW"):
    """Zero-pad H/W (reference zeropad2d): padding = [left, right, top,
    bottom]."""
    l, r, t, b = (padding if not hasattr(padding, "tolist")
                  else padding.tolist())

    def impl(a):
        if data_format == "NCHW":
            return jnp.pad(a, [(0, 0), (0, 0), (t, b), (l, r)])
        return jnp.pad(a, [(0, 0), (t, b), (l, r), (0, 0)])
    return apply_op("zeropad2d", impl, (x,), {})
