"""Activation functionals (reference: python/paddle/nn/functional/activation.py;
kernels paddle/phi/kernels/activation_kernel.*). XLA fuses these into adjacent
matmuls — no hand-fused bias+act kernel needed on TPU for the common cases."""
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _op(name, impl, *args, **kwargs):
    return apply_op(name, impl, args, kwargs)


def relu(x):
    return _op("relu", jax.nn.relu, x)


def relu6(x):
    return _op("relu6", jax.nn.relu6, x)


def relu_(x):
    out = relu(x)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient and x.stop_gradient
    return x


def gelu(x, approximate=False):
    return _op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def silu(x):
    return _op("silu", jax.nn.silu, x)


swish = silu


def sigmoid(x):
    return _op("sigmoid", jax.nn.sigmoid, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return _op("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def hardswish(x):
    return _op("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0):
    return _op("hardtanh", lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5):
    return _op("hardshrink",
               lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5):
    def impl(a):
        return jnp.where(a > threshold, a - threshold,
                         jnp.where(a < -threshold, a + threshold, 0.0))
    return _op("softshrink", impl, x)


def tanhshrink(x):
    return _op("tanhshrink", lambda a: a - jnp.tanh(a), x)


def thresholded_relu(x, threshold=1.0, value=0.0):
    return _op("thresholded_relu",
               lambda a: jnp.where(a > threshold, a, value), x)


def leaky_relu(x, negative_slope=0.01):
    return _op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0):
    return _op("elu", lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return _op("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0):
    return _op("celu", lambda a: jax.nn.celu(a, alpha), x)


def mish(x):
    return _op("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def softplus(x, beta=1.0, threshold=20.0):
    def impl(a):
        scaled = beta * a
        return jnp.where(scaled > threshold, a, jax.nn.softplus(scaled) / beta)
    return _op("softplus", impl, x)


def softsign(x):
    return _op("softsign", jax.nn.soft_sign, x)


def tanh(x):
    return _op("tanh", jnp.tanh, x)


def softmax(x, axis=-1, dtype=None):
    def impl(a):
        if dtype is not None:
            from ...core.dtypes import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return _op("softmax", impl, x)


def log_softmax(x, axis=-1, dtype=None):
    def impl(a):
        if dtype is not None:
            from ...core.dtypes import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return _op("log_softmax", impl, x)


def log_sigmoid(x):
    return _op("log_sigmoid", jax.nn.log_sigmoid, x)


def glu(x, axis=-1):
    def impl(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return _op("glu", impl, x)


def prelu(x, weight, data_format="NCHW"):
    def impl(a, w):
        if w.size == 1:
            w_b = w.reshape(())
        elif data_format == "NCHW" and a.ndim > 2:
            w_b = w.reshape((1, -1) + (1,) * (a.ndim - 2))
        else:
            w_b = w
        return jnp.where(a > 0, a, w_b * a)
    return _op("prelu", impl, x, weight)


def maxout(x, groups, axis=1):
    def impl(a):
        axis_ = axis % a.ndim
        c = a.shape[axis_]
        new_shape = (a.shape[:axis_] + (c // groups, groups) + a.shape[axis_ + 1:])
        return jnp.max(a.reshape(new_shape), axis=axis_ + 1)
    return _op("maxout", impl, x)


def rrelu(x, lower=0.125, upper=0.3333333, training=True):
    from ...core import random as _random
    if training:
        def impl(a):
            k = _random.next_key()
            slope = jax.random.uniform(k, a.shape, jnp.float32, lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, slope * a)
        return _op("rrelu", impl, x)
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def _inplace(base):
    def fn(x, *args, **kwargs):
        out = base(x, *args, **kwargs)
        x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
        x.stop_gradient = out.stop_gradient and x.stop_gradient
        return x
    fn.__name__ = base.__name__ + "_"
    return fn


elu_ = _inplace(elu)
hardtanh_ = _inplace(hardtanh)
leaky_relu_ = _inplace(leaky_relu)
softmax_ = _inplace(softmax)
tanh_ = _inplace(tanh)
thresholded_relu_ = _inplace(thresholded_relu)
