"""Rotary position embedding (reference: paddle/phi/kernels/fusion/gpu/
fused_rope_kernel.cu + python/paddle/incubate/nn/functional/
fused_rotary_position_embedding.py).

TPU-native: RoPE is a bandwidth-bound elementwise op sandwiched between the
QKV projection and attention — exactly what XLA fuses into neighbours for
free, so the "fused" kernel here is a jnp expression (the Pallas flash kernel
can also absorb it). Layout matches paddle: [batch, seq, heads, head_dim].
"""
import jax.numpy as jnp

from ...core.dispatch import apply_op

__all__ = [
    "rotary_embedding_cos_sin", "apply_rotary_pos_emb",
    "fused_rotary_position_embedding",
]


def rotary_embedding_cos_sin(seq_len, head_dim, base=10000.0,
                             position_ids=None, dtype=jnp.float32):
    """cos/sin tables [seq, head_dim//2] (fp32 accumulation, cast by caller)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
        freqs = jnp.outer(t, inv_freq)                      # [S, D/2]
    else:
        freqs = position_ids[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def _rotate(x, cos, sin, use_neox):
    """x: [B, S, H, D]; cos/sin: [S, D/2] or [B, S, D/2] broadcastable."""
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:  # [B, S, D/2] from position_ids
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    if use_neox:
        # neox style: rotate [x_{0:D/2}, x_{D/2:D}] halves
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([x1 * cos - x2 * sin,
                                x2 * cos + x1 * sin], axis=-1)
    # GPT-J / interleaved style: rotate even/odd pairs
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.reshape(x.shape)


def apply_rotary_pos_emb(x, cos, sin, use_neox_rotary_style=True):
    cdtype = x.dtype

    def impl(a, c, s):
        return _rotate(a, c.astype(cdtype), s.astype(cdtype),
                       use_neox_rotary_style)
    return apply_op("rope", impl, (x, cos, sin), {})


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    rotary_emb_base=10000.0):
    """Parity with paddle.incubate.nn.functional.fused_rotary_position_embedding:
    q/k/v are [B, S, H, D]; returns rotated (q, k, v) (v passes through when
    given, matching the reference's optional-rotation contract)."""
    head_dim = int(q.shape[-1])
    seq_len = int(q.shape[1])
    if cos is None or sin is None:
        cos, sin = rotary_embedding_cos_sin(
            seq_len, head_dim, base=rotary_emb_base, position_ids=position_ids)
    else:
        # paddle passes [1, S_max, 1, D] tables; reduce to canonical [S, D/2]
        # respecting the pair layout: neox duplicates halves ([f, f]), GPT-J
        # interleaves pairs ([f0, f0, f1, f1, ...])
        cos = jnp.asarray(cos.data if hasattr(cos, "data") else cos)
        sin = jnp.asarray(sin.data if hasattr(sin, "data") else sin)
        cos = cos.reshape(cos.shape[-3], cos.shape[-1])
        sin = sin.reshape(sin.shape[-3], sin.shape[-1])
        if use_neox_rotary_style:
            cos, sin = cos[:, : head_dim // 2], sin[:, : head_dim // 2]
        else:
            cos, sin = cos[:, 0::2], sin[:, 0::2]
        if position_ids is not None:
            # decode path: gather the rows for the requested positions
            # (reference fused_rope gathers sin/cos by position_ids)
            cos = jnp.take(cos, position_ids, axis=0)   # [B, S, D/2]
            sin = jnp.take(sin, position_ids, axis=0)
        elif cos.shape[0] != seq_len:
            cos, sin = cos[:seq_len], sin[:seq_len]
    outs = [apply_rotary_pos_emb(q, cos, sin, use_neox_rotary_style)]
    outs.append(apply_rotary_pos_emb(k, cos, sin, use_neox_rotary_style)
                if k is not None else None)
    outs.append(v)
    return tuple(outs)
