"""Common functionals: linear, dropout, embedding, normalize, interpolate,
similarity (reference: python/paddle/nn/functional/common.py, input.py)."""
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core import random as _random


def _op(name, impl, *args, **kwargs):
    return apply_op(name, impl, args, kwargs)


def linear(x, weight, bias=None):
    """paddle convention: weight is [in_features, out_features]."""
    if bias is None:
        return _op("linear", lambda a, w: jnp.matmul(a, w), x, weight)
    return _op("linear", lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train"):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return _op("dropout_scale", lambda a: a * (1.0 - p), x)
        return x

    def impl(a, key):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    # key as an input leaf: fresh per call in eager and under SOT replay
    # (the whole-function jit tier still bakes the trace-time key)
    return _op("dropout", impl, x, _random.fresh_key_tensor())


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def impl(a):
        key = _random.next_key()
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef
    return _op("alpha_dropout", impl, x)


def embedding(x, weight, padding_idx=None, sparse=False):
    def impl(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out

    from ...core import autograd as _ag
    from ...core import dispatch as _dispatch
    # the SelectedRows fast path bypasses apply_op (its vjp returns a
    # sparse object the dispatch vjp contract can't express), which makes
    # the op invisible to graph capture — under an active SOT/static
    # recorder that means a stale pinned output on replay. Capture planes
    # therefore get the dense path (correct, just dense grads).
    capture_active = (_dispatch._sir_recorder is not None
                      or _dispatch._static_recorder is not None)
    if sparse and not capture_active and _ag.is_grad_enabled() \
            and not weight.stop_gradient \
            and not isinstance(weight.data, jax.core.Tracer):
        # sparse=True: the weight gradient is a SelectedRows (rows = the
        # looked-up ids, values = output cotangent rows) instead of a dense
        # [V, D] scatter (reference: embedding_sparse_grad kernel +
        # SelectedRows grads, phi/kernels/selected_rows/)
        from ...core.tensor import Tensor
        from ...core.autograd import GradNode
        from ...core.selected_rows import SelectedRows
        idx_arr = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        w_arr = weight.data
        out = impl(idx_arr, w_arr)

        def vjp_fn(ct):
            rows = idx_arr.reshape(-1)
            vals = jnp.reshape(ct, (-1, ct.shape[-1]))
            if padding_idx is not None:
                vals = jnp.where((rows == padding_idx)[:, None],
                                 jnp.zeros((), vals.dtype), vals)
            return (SelectedRows(rows, vals, w_arr.shape[0]),)

        node = GradNode("embedding_sparse", vjp_fn, [weight],
                        [(out.shape, out.dtype)])
        t = Tensor(out, stop_gradient=False)
        t._node = node
        t._out_idx = 0
        for _l in list(_dispatch._op_listeners):
            _l("embedding_sparse", 2, t)
        return t
    return _op("embedding", impl, x, weight)


def one_hot(x, num_classes):
    return _op("one_hot",
               lambda a: jax.nn.one_hot(a, int(num_classes), dtype=jnp.float32), x,
               )


def normalize(x, p=2, axis=1, epsilon=1e-12):
    def impl(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return _op("normalize", impl, x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def impl(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return _op("cosine_similarity", impl, x1, x2)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    def impl(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return _op("pairwise_distance", impl, x, y)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    """2D resize (nearest / bilinear / bicubic) via jax.image."""
    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
        else:
            n, h, w, c = a.shape
        if size is not None:
            out_h, out_w = int(size[0]), int(size[1])
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else (scale_factor, scale_factor)
            out_h, out_w = int(h * sf[0]), int(w * sf[1])
        method = {"nearest": "nearest", "bilinear": "linear",
                  "bicubic": "cubic", "linear": "linear"}[mode]
        if data_format == "NCHW":
            out = jax.image.resize(a, (n, c, out_h, out_w), method=method)
        else:
            out = jax.image.resize(a, (n, out_h, out_w, c), method=method)
        return out
    return _op("interpolate", impl, x)


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = int(upscale_factor)

    def impl(a):
        if data_format != "NCHW":
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        oc = c // (r * r)
        out = a.reshape(n, oc, r, r, h, w)
        out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
        out = out.reshape(n, oc, h * r, w * r)
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return _op("pixel_shuffle", impl, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = int(downscale_factor)

    def impl(a):
        if data_format != "NCHW":
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        out = a.reshape(n, c, h // r, r, w // r, r)
        out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
        out = out.reshape(n, c * r * r, h // r, w // r)
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return _op("pixel_unshuffle", impl, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference: unfold op). Returns [N, C*kh*kw, L]."""
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) \
        else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    ph, pw = (paddings, paddings) if isinstance(paddings, int) else paddings[:2]
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations

    def impl(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        out_h = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        out_w = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        cols = []
        for i in range(kh):
            for j in range(kw):
                patch = a[:, :, i * dh: i * dh + out_h * sh: sh,
                          j * dw: j * dw + out_w * sw: sw]
                cols.append(patch.reshape(n, c, -1))
        out = jnp.stack(cols, axis=2)  # [N, C, kh*kw, L]
        return out.reshape(n, c * kh * kw, -1)
    return _op("unfold", impl, x)


def label_smooth(label, prior_dist=None, epsilon=0.1):
    def impl(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist.data if hasattr(prior_dist, "data") else prior_dist
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k
    return _op("label_smooth", impl, label)
