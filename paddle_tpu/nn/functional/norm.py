"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
phi kernels batch_norm/layer_norm/group_norm + fused_layernorm in §2.9 of the
survey — on TPU, XLA fuses the normalization math; a Pallas fused RMSNorm
covers the long-row case)."""
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None):
    """Reference semantics (paddle/phi/kernels/batch_norm_kernel.h): in
    training mode uses batch statistics and updates running stats in place;
    in eval uses running stats."""
    if use_global_stats is None:
        use_global_stats = not training
    ch_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else -1
    axes = tuple(i for i in range(x.ndim) if i != (ch_axis % x.ndim))

    def shape_c(a):
        s = [1] * x.ndim
        s[ch_axis % x.ndim] = -1
        return a.reshape(s)

    if not use_global_stats:
        # batch stats; update running stats host-side (eager semantics)
        def impl(a, *wb):
            mean = jnp.mean(a, axis=axes)
            var = jnp.var(a, axis=axes)
            out = (a - shape_c(mean)) / jnp.sqrt(shape_c(var) + epsilon)
            if len(wb) == 2:
                out = out * shape_c(wb[0]) + shape_c(wb[1])
            return out, mean, var
        args = (x,) if weight is None else (x, weight, bias)
        out, mean, var = apply_op("batch_norm", impl, args, {})
        if isinstance(running_mean, Tensor) and not isinstance(mean.data, jax.core.Tracer):
            m = momentum
            running_mean.set_value(m * running_mean.data + (1 - m) * mean.data)
            running_var.set_value(m * running_var.data + (1 - m) * var.data)
        return out

    def impl(a, rm, rv, *wb):
        out = (a - shape_c(rm)) / jnp.sqrt(shape_c(rv) + epsilon)
        if len(wb) == 2:
            out = out * shape_c(wb[0]) + shape_c(wb[1])
        return out
    args = (x, running_mean, running_var) if weight is None \
        else (x, running_mean, running_var, weight, bias)
    return apply_op("batch_norm_infer", impl, args, {})


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)
    axes = tuple(range(-n_axes, 0))

    def impl(a, *wb):
        dtype = a.dtype
        a32 = a.astype(jnp.float32)  # fp32 statistics, output in input dtype
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        out = ((a32 - mean) * jax.lax.rsqrt(var + epsilon)).astype(dtype)
        if len(wb) >= 1 and wb[0] is not None:
            out = out * wb[0].astype(dtype)
        if len(wb) == 2 and wb[1] is not None:
            out = out + wb[1].astype(dtype)
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply_op("layer_norm", impl, tuple(args), {})


def rms_norm(x, weight=None, epsilon=1e-6):
    """RMSNorm (capability beyond the snapshot's python surface; the reference
    carries fused_rms_norm in fused_ops.yaml). Hot path for Llama."""
    def impl(a, *w):
        dtype = a.dtype
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(a32 * a32, axis=-1, keepdims=True)
        out = a32 * jax.lax.rsqrt(ms + epsilon)
        out = out.astype(dtype)
        if w:
            # keep the compute dtype (a fp32 scale must not promote a bf16
            # activation — that would silently turn the whole network fp32)
            out = out * w[0].astype(dtype)
        return out
    args = (x,) if weight is None else (x, weight)
    return apply_op("rms_norm", impl, args, {})


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    def impl(a, *wb):
        n, c = a.shape[0], a.shape[1]
        spatial = a.shape[2:]
        g = num_groups
        out = a.reshape(n, g, c // g, *spatial)
        axes = tuple(range(2, out.ndim))
        mean = jnp.mean(out, axis=axes, keepdims=True)
        var = jnp.var(out, axis=axes, keepdims=True)
        out = (out - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.reshape(a.shape)
        if wb:
            shape = (1, c) + (1,) * len(spatial)
            out = out * wb[0].reshape(shape)
            if len(wb) == 2:
                out = out + wb[1].reshape(shape)
        return out
    if data_format not in ("NCHW", "NCL", "NCDHW"):
        # channels-last (NHWC/NLC/NDHWC): normalize via the channels-first
        # path with a transpose pair XLA folds into the surrounding ops
        nd = x.ndim
        to_cf = (0, nd - 1) + tuple(range(1, nd - 1))
        to_cl = (0,) + tuple(range(2, nd)) + (1,)
        out = group_norm(x.transpose(to_cf), num_groups, weight=weight,
                         bias=bias, epsilon=epsilon, data_format="NCHW")
        return out.transpose(to_cl)
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply_op("group_norm", impl, tuple(args), {})


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW"):
    def impl(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        if wb:
            c = a.shape[1]
            shape = (1, c) + (1,) * (a.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) == 2:
                out = out + wb[1].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply_op("instance_norm", impl, tuple(args), {})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    def impl(a):
        sq = a * a
        half = size // 2
        # sum over channel window
        pad = [(0, 0)] * a.ndim
        pad[1] = (half, size - 1 - half)
        sq = jnp.pad(sq, pad)
        acc = sum(sq[:, i:i + a.shape[1]] for i in range(size))
        return a / (k + alpha * acc) ** beta
    return apply_op("local_response_norm", impl, (x,), {})
