"""Attention functionals.

Reference surface: python/paddle/nn/functional/flash_attention.py:358
(flash_attention), :756 (flash_attn_unpadded), :1299 (flashmask_attention),
scaled_dot_product_attention. On TPU the fused kernel is a Pallas flash
kernel (paddle_tpu/ops/pallas/flash_attention.py, M7 tier); this module holds
the API and the XLA reference path used on CPU / for small shapes.
"""
import math
import functools

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core import random as _random

_USE_PALLAS = True  # flipped off on CPU automatically inside _flash_available


@functools.lru_cache(maxsize=1)
def _flash_available():
    try:
        return _USE_PALLAS and jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _sdpa_ref(q, k, v, mask=None, dropout=0.0, causal=False, scale=None,
              training=True):
    """Reference attention in pure XLA ops, [B, S, H, D] layout (paddle's
    flash_attention layout)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if k.shape[2] != q.shape[2]:  # GQA: broadcast KV head groups
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # [B,S,H,D] -> [B,H,S,D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # QK logits and the prob·V reduction accumulate in f32 (MXU-native
    # bf16-in/f32-accumulate); only the final output is cast back.
    logits = jnp.einsum("bhsd,bhtd->bhst", qt, kt,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((s, t), dtype=bool), k=t - s)
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout > 0.0 and training:
        keep = jax.random.bernoulli(_random.next_key(), 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    out = jnp.einsum("bhst,bhtd->bhsd", probs,
                     vt.astype(jnp.float32)).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, training=True):
    """paddle.nn.functional.flash_attention.flash_attention parity:
    inputs [batch, seqlen, num_heads, head_dim]; returns (out, softmax|None).

    On TPU dispatches to the Pallas flash kernel (M7); elsewhere uses the XLA
    reference path (XLA fuses it reasonably; the Pallas kernel wins at long
    sequence)."""
    if _flash_available() and dropout == 0.0 and not return_softmax:
        from ...ops.pallas import flash_attention as pallas_flash
        try:
            def impl(q, k, v):
                return pallas_flash.flash_attention_bshd(q, k, v, causal=causal)
            out = apply_op("flash_attention", impl, (query, key, value), {})
            return out, None
        except Exception:
            pass  # fall through to reference path

    def impl(q, k, v):
        return _sdpa_ref(q, k, v, dropout=dropout, causal=causal,
                         training=training)
    out = apply_op("flash_attention_ref", impl, (query, key, value), {})
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True):
    """paddle.nn.functional.scaled_dot_product_attention parity
    ([B, S, H, D] layout, additive or bool mask)."""
    if attn_mask is None:
        out, _ = flash_attention(query, key, value, dropout=dropout_p,
                                 causal=is_causal, training=training)
        return out

    def impl(q, k, v, m):
        return _sdpa_ref(q, k, v, mask=m, dropout=dropout_p, causal=is_causal,
                         training=training)
    return apply_op("sdpa", impl, (query, key, value, attn_mask), {})


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=True):
    """FlashMask (reference python/paddle/nn/functional/flash_attention.py:1299):
    column-sparse mask attention for long context. The mask is given as
    start/end row indices per column: position (r, c) is masked out when
    r >= start[c] (LTS) etc. Reference path materializes the mask; the Pallas
    kernel (M7+) consumes indices directly."""
    if startend_row_indices is None:
        out, _ = flash_attention(query, key, value, dropout=dropout, causal=causal)
        return out

    def impl(q, k, v, idx):
        s = q.shape[1]
        rows = jnp.arange(s)[:, None]  # query row index
        # LTS convention: column c masks query rows r >= start[c]
        start = idx[..., 0]  # [B, nh, S_k]
        keep = rows[None, None] < start[:, :, None, :]
        if causal:
            cm = jnp.tril(jnp.ones((s, s), dtype=bool))
            keep = jnp.logical_and(keep, cm)
        return _sdpa_ref(q, k, v, mask=keep, dropout=dropout, causal=False)
    return apply_op("flashmask_attention", impl,
                    (query, key, value, startend_row_indices), {})


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True):
    """Var-len attention (reference flash_attn_unpadded, :756): packed
    [total_tokens, H, D] with cumulative sequence offsets. XLA wants static
    shapes, so this builds a segment mask over the packed layout — the
    idiomatic TPU equivalent of varlen flash (segment-ids pattern)."""
    def impl(q, k, v, cu_q, cu_k):
        total_q = q.shape[0]
        total_k = k.shape[0]
        pos_q = jnp.arange(total_q)
        pos_k = jnp.arange(total_k)
        seg_q = jnp.searchsorted(cu_q[1:], pos_q, side="right")
        seg_k = jnp.searchsorted(cu_k[1:], pos_k, side="right")
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            off_q = pos_q - jnp.take(cu_q, seg_q)
            off_k = pos_k - jnp.take(cu_k, seg_k)
            mask = jnp.logical_and(mask, off_q[:, None] >= off_k[None, :])
        d = q.shape[-1]
        sc = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("shd,thd->hst", q, k) * sc
        logits = jnp.where(mask[None], logits, jnp.finfo(logits.dtype).min)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
        if dropout > 0.0 and training:
            keep = jax.random.bernoulli(_random.next_key(), 1.0 - dropout, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
        return jnp.einsum("hst,thd->shd", probs, v)
    out = apply_op("flash_attn_unpadded", impl,
                   (query, key, value, cu_seqlens_q, cu_seqlens_k), {})
    return out, None
