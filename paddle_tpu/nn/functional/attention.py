"""Attention functionals.

Reference surface: python/paddle/nn/functional/flash_attention.py:358
(flash_attention), :756 (flash_attn_unpadded), :1299 (flashmask_attention),
scaled_dot_product_attention. On TPU the fused kernel is a Pallas flash
kernel (paddle_tpu/ops/pallas/flash_attention.py, M7 tier); this module holds
the API and the XLA reference path used on CPU / for small shapes.
"""
import math
import functools

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core import random as _random

_USE_PALLAS = True  # flipped off on CPU automatically inside _flash_available


@functools.lru_cache(maxsize=1)
def _flash_available():
    try:
        return _USE_PALLAS and jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _sdpa_ref(q, k, v, mask=None, dropout=0.0, causal=False, scale=None,
              rng_key=None):
    """Reference attention in pure XLA ops, [B, S, H, D] layout (paddle's
    flash_attention layout).

    Dropout requires `rng_key` (a PRNG key array passed in as an *input*,
    never drawn inside this function). Keeping the impl RNG-free is the
    philox-offset discipline (reference paddle/phi/core/generator.h:32):
    the eager vjp cache rematerialises the forward inside its jitted
    backward, and a key passed as an input replays identically there, while
    an internal draw would leak a tracer into the global key chain."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if k.shape[2] != q.shape[2]:  # GQA: broadcast KV head groups
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # [B,S,H,D] -> [B,H,S,D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # QK logits and the prob·V reduction accumulate in f32 (MXU-native
    # bf16-in/f32-accumulate); only the final output is cast back.
    logits = jnp.einsum("bhsd,bhtd->bhst", qt, kt,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((s, t), dtype=bool), k=t - s)
        logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout > 0.0 and rng_key is not None:
        keep = jax.random.bernoulli(rng_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    out = jnp.einsum("bhst,bhtd->bhsd", probs,
                     vt.astype(jnp.float32)).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    training=True, rope_cos=None, rope_sin=None):
    """paddle.nn.functional.flash_attention.flash_attention parity:
    inputs [batch, seqlen, num_heads, head_dim]; returns (out, softmax|None).

    On TPU dispatches to the Pallas flash kernel (M7); elsewhere uses the XLA
    reference path (XLA fuses it reasonably; the Pallas kernel wins at long
    sequence). rope_cos/rope_sin [S, D/2] (neox): applied to q/k INSIDE the
    Pallas kernels when available, otherwise rotated before the reference
    path — either way rotated q/k are an implementation detail."""
    if _flash_available() and dropout == 0.0 and not return_softmax:
        from ...ops.pallas import flash_attention as pallas_flash
        try:
            bq, bk = pallas_flash.tuned_blocks(query, key, value, causal)

            def impl(q, k, v, rc=None, rs=None):
                return pallas_flash.flash_attention_bshd(
                    q, k, v, causal=causal, block_q=bq, block_k=bk,
                    rope_cos=rc, rope_sin=rs)

            if rope_cos is None:
                args = (query, key, value)
            else:
                args = (query, key, value, rope_cos, rope_sin)
            out = apply_op("flash_attention", impl, args, {})
            return out, None
        except Exception:
            pass  # fall through to reference path
    if rope_cos is not None:
        # non-kernel path: rotate explicitly (same math, materialized)
        from .rope import apply_rotary_pos_emb
        query = apply_rotary_pos_emb(query, rope_cos, rope_sin, True)
        key = apply_rotary_pos_emb(key, rope_cos, rope_sin, True)

    if dropout > 0.0 and training:
        def impl(q, k, v, rk):
            return _sdpa_ref(q, k, v, dropout=dropout, causal=causal,
                             rng_key=rk)
        out = apply_op("flash_attention_ref", impl,
                       (query, key, value, _random.fresh_key_tensor()), {})
        return out, None

    def impl(q, k, v):
        return _sdpa_ref(q, k, v, causal=causal)
    out = apply_op("flash_attention_ref", impl, (query, key, value), {})
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True):
    """paddle.nn.functional.scaled_dot_product_attention parity
    ([B, S, H, D] layout, additive or bool mask)."""
    if attn_mask is None:
        out, _ = flash_attention(query, key, value, dropout=dropout_p,
                                 causal=is_causal, training=training)
        return out

    if dropout_p > 0.0 and training:
        def impl(q, k, v, m, rk):
            return _sdpa_ref(q, k, v, mask=m, dropout=dropout_p,
                             causal=is_causal, rng_key=rk)
        return apply_op("sdpa", impl, (query, key, value, attn_mask,
                                       _random.fresh_key_tensor()), {})

    def impl(q, k, v, m):
        return _sdpa_ref(q, k, v, mask=m, causal=is_causal)
    return apply_op("sdpa", impl, (query, key, value, attn_mask), {})


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=True):
    """FlashMask (reference python/paddle/nn/functional/flash_attention.py:1299):
    column-sparse mask attention for long context. The mask is given as
    start/end row indices per column: position (r, c) is masked out when
    r >= start[c] (LTS) etc. Reference path materializes the mask; the Pallas
    kernel (M7+) consumes indices directly."""
    if startend_row_indices is None:
        out, _ = flash_attention(query, key, value, dropout=dropout, causal=causal)
        return out

    def impl(q, k, v, idx, *rk):
        s = q.shape[1]
        rows = jnp.arange(s)[:, None]  # query row index
        # LTS convention: column c masks query rows r >= start[c]
        start = idx[..., 0]  # [B, nh, S_k]
        keep = rows[None, None] < start[:, :, None, :]
        if causal:
            cm = jnp.tril(jnp.ones((s, s), dtype=bool))
            keep = jnp.logical_and(keep, cm)
        return _sdpa_ref(q, k, v, mask=keep, dropout=dropout, causal=False,
                         rng_key=rk[0] if rk else None)
    args = (query, key, value, startend_row_indices)
    if dropout > 0.0:
        args = args + (_random.fresh_key_tensor(),)
    return apply_op("flashmask_attention", impl, args, {})


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True):
    """Var-len attention (reference flash_attn_unpadded, :756): packed
    [total_tokens, H, D] with cumulative sequence offsets. XLA wants static
    shapes, so this builds a segment mask over the packed layout — the
    idiomatic TPU equivalent of varlen flash (segment-ids pattern)."""
    def impl(q, k, v, cu_q, cu_k, *rk):
        total_q = q.shape[0]
        total_k = k.shape[0]
        pos_q = jnp.arange(total_q)
        pos_k = jnp.arange(total_k)
        seg_q = jnp.searchsorted(cu_q[1:], pos_q, side="right")
        seg_k = jnp.searchsorted(cu_k[1:], pos_k, side="right")
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            off_q = pos_q - jnp.take(cu_q, seg_q)
            off_k = pos_k - jnp.take(cu_k, seg_k)
            mask = jnp.logical_and(mask, off_q[:, None] >= off_k[None, :])
        d = q.shape[-1]
        sc = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("shd,thd->hst", q, k) * sc
        logits = jnp.where(mask[None], logits, jnp.finfo(logits.dtype).min)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
        if rk:
            keep = jax.random.bernoulli(rk[0], 1.0 - dropout, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
        return jnp.einsum("hst,thd->shd", probs, v)
    args = (query, key, value, cu_seqlens_q, cu_seqlens_k)
    if dropout > 0.0 and training:
        args = args + (_random.fresh_key_tensor(),)
    out = apply_op("flash_attn_unpadded", impl, args, {})
    return out, None


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, training=True):
    """Packed-QKV flash attention (reference flash_attn_qkvpacked):
    qkv [B, S, 3 + 2*(G-1)... ] — paddle layout [B, S, 3, H, D] for MHA;
    unpacks and dispatches to flash_attention."""
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, return_softmax=False,
                                training=True):
    """Packed var-len form (reference flash_attn_varlen_qkvpacked):
    qkv [total_tokens, 3, H, D]."""
    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale=scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax,
                               training=training)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None):
    """Block/CSR-sparse attention (reference sparse_attention op): per-row
    allowed key columns given in CSR form. TPU-native path: scatter the CSR
    pattern into a dense boolean mask and run the fused softmax path — XLA
    handles the [S, S] mask well below ~16k; beyond that use
    flashmask_attention (interval masks) which the Pallas tier consumes
    directly."""
    def impl(q, k, v, off, cols, *masks):
        b, h, s, d = q.shape
        # build mask by scattering: for each row r, cols[off[r]:off[r+1]]
        dense = jnp.zeros((b, h, s, s), bool)
        offs = off.reshape(b, h, s + 1)
        colv = cols.reshape(b, h, -1)
        pos = jnp.arange(colv.shape[-1])
        # row of entry i = #rows whose end-offset is <= i
        rows = (pos[None, None, :, None]
                >= offs[:, :, None, 1:]).sum(-1)      # [B,H,nnz]
        valid = pos[None, None] < offs[..., -1:]
        bidx = jnp.arange(b)[:, None, None]
        hidx = jnp.arange(h)[None, :, None]
        # padding entries are pointed out of bounds and dropped — writing
        # False at a clamped (0,0) could clobber a real allowed pair
        dense = dense.at[bidx, hidx,
                         jnp.where(valid, rows, s),
                         jnp.where(valid, colv, s)].set(True, mode="drop")
        import math as _m
        logits = jnp.einsum("bhsd,bhtd->bhst", q, k) / _m.sqrt(d)
        if masks and masks[0] is not None:
            logits = logits + masks[0]
        logits = jnp.where(dense, logits, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bhst,bhtd->bhsd", probs, v)
    args = (query, key, value, sparse_csr_offset, sparse_csr_columns)
    if attn_mask is not None:
        args = args + (attn_mask,)
    return apply_op("sparse_attention", impl, args, {})
