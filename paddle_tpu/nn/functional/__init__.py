"""paddle.nn.functional surface (reference: python/paddle/nn/functional/)."""
from .activation import (
    relu, relu_, relu6, gelu, silu, swish, sigmoid, hardsigmoid, hardswish,
    hardtanh, hardshrink, softshrink, tanhshrink, thresholded_relu, leaky_relu,
    elu, selu, celu, mish, softplus, softsign, tanh, softmax, log_softmax,
    log_sigmoid, glu, prelu, maxout, rrelu,
    elu_, hardtanh_, leaky_relu_, softmax_, tanh_, thresholded_relu_,
)
from .common import (
    linear, dropout, dropout2d, dropout3d, alpha_dropout, embedding, one_hot,
    normalize, cosine_similarity, pairwise_distance, interpolate, upsample,
    pixel_shuffle, pixel_unshuffle, unfold, label_smooth,
)
from .conv import (conv1d, conv2d, conv3d, conv1d_transpose,
                   conv2d_transpose, conv3d_transpose)
from .extra import (bilinear, pdist, feature_alpha_dropout, channel_shuffle,
                    affine_grid, grid_sample, fold, sequence_mask,
                    temporal_shift, gumbel_softmax, npair_loss, ctc_loss,
                    gather_tree, class_center_sample, zeropad2d)
from .pooling import (
    max_pool1d, max_pool2d, max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_max_pool2d,
    adaptive_avg_pool3d, adaptive_max_pool1d, adaptive_max_pool3d,
    lp_pool1d, lp_pool2d, max_unpool1d, max_unpool2d, max_unpool3d,
    fractional_max_pool2d, fractional_max_pool3d,
)
from .norm import (
    batch_norm, layer_norm, rms_norm, group_norm, instance_norm,
    local_response_norm,
)
from .loss import (
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, huber_loss, binary_cross_entropy,
    binary_cross_entropy_with_logits, kl_div, margin_ranking_loss,
    hinge_embedding_loss, cosine_embedding_loss, triplet_margin_loss,
    sigmoid_focal_loss, log_loss, square_error_cost,
    dice_loss, soft_margin_loss, multi_label_soft_margin_loss,
    multi_margin_loss, poisson_nll_loss, gaussian_nll_loss,
    triplet_margin_with_distance_loss, hsigmoid_loss, margin_cross_entropy,
    rnnt_loss, adaptive_log_softmax_with_loss,
)
from .attention import (
    flash_attention, scaled_dot_product_attention, flashmask_attention,
    flash_attn_unpadded, flash_attn_qkvpacked, flash_attn_varlen_qkvpacked,
    sparse_attention,
)
from .rope import (
    rotary_embedding_cos_sin, apply_rotary_pos_emb,
    fused_rotary_position_embedding,
)

# ops that live in the core registry but are also exposed via F (paddle parity)
from ...ops import pad  # noqa: F401
