"""Pooling (reference: python/paddle/nn/functional/pooling.py; phi pool
kernels). lax.reduce_window is the XLA-native pooling primitive. ceil_mode is
implemented by extending the high-side padding (with -inf for max, with
count-corrected zeros for avg) — reduce_window itself is floor-mode."""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _resolve_pads(kernel, stride, padding, ceil_mode, in_sizes):
    """Per-spatial-dim (lo, hi) pads, with hi extended for ceil_mode."""
    n = len(in_sizes)
    k = _tup(kernel, n)
    s = _tup(stride, n)
    p = _tup(padding, n)
    pads = []
    for i in range(n):
        hi = p[i]
        if ceil_mode:
            span = in_sizes[i] + 2 * p[i] - k[i]
            rem = span % s[i]
            if rem:
                hi += s[i] - rem
        pads.append((p[i], hi))
    return k, s, pads


def _pool_nd(x, kernel, stride, padding, spatial, kind, name, ceil_mode=False,
             exclusive=True, divisor_override=None):
    if stride is None:
        stride = kernel
    if isinstance(padding, str):
        window = (1, 1) + _tup(kernel, spatial)
        strides = (1, 1) + _tup(stride, spatial)
        pad = padding.upper()

        def impl_str(a):
            if kind == "max":
                return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window,
                                             strides, pad)
            out = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad)
            return out / int(np.prod(_tup(kernel, spatial)))
        return apply_op(name, impl_str, (x,), {})

    def impl(a):
        in_sizes = a.shape[2:]
        k, s, sp_pads = _resolve_pads(kernel, stride, padding, ceil_mode, in_sizes)
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + sp_pads
        if kind == "max":
            return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window,
                                         strides, pads)
        out = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pads)
        if divisor_override:
            return out / divisor_override
        padded = any(lo or hi for lo, hi in sp_pads)
        if exclusive and padded:
            counts = jax.lax.reduce_window(jnp.ones_like(a), 0.0, jax.lax.add,
                                           window, strides, pads)
            return out / counts
        if padded and ceil_mode:
            # include_pad but ceil: the ceil-extension region must still be
            # excluded (paddle counts only the declared padding)
            base = [(lo, lo) for lo, _ in sp_pads]
            ones = jnp.pad(jnp.ones_like(a), [(0, 0), (0, 0)] + base,
                           constant_values=1.0)  # declared pad counts
            extra = [(0, hi - lo) for lo, hi in sp_pads]
            ones = jnp.pad(ones, [(0, 0), (0, 0)] + extra)  # ceil region doesn't
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                           strides, [(0, 0)] * (spatial + 2))
            return out / jnp.maximum(counts, 1.0)
        return out / int(np.prod(k))
    return apply_op(name, impl, (x,), {})


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    out = _pool_nd(x, kernel_size, stride, padding, 2, "max", "max_pool2d",
                   ceil_mode=ceil_mode)
    if return_mask:
        return out, _max_pool2d_indices(x, kernel_size, stride, padding)
    return out


def _max_pool2d_indices(x, kernel_size, stride, padding):
    kh, kw = _tup(kernel_size, 2)
    if stride is None:
        stride = kernel_size

    def impl(a):
        n, c, h, w = a.shape
        flat_idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
        flat_idx = jnp.broadcast_to(flat_idx, a.shape)

        def reducer(xv, yv):
            xval, xidx = xv
            yval, yidx = yv
            take_y = yval > xval
            return (jnp.where(take_y, yval, xval), jnp.where(take_y, yidx, xidx))
        sh, sw = _tup(stride, 2)
        ph, pw = _tup(padding, 2) if not isinstance(padding, str) else (0, 0)
        _, out_i = jax.lax.reduce_window(
            (a, flat_idx), (-jnp.inf, jnp.float32(-1)), reducer,
            (1, 1, kh, kw), (1, 1, sh, sw),
            [(0, 0), (0, 0), (ph, ph), (pw, pw)])
        return out_i.astype(jnp.int32)
    return apply_op("max_pool2d_indices", impl, (x,), {}, differentiable=False)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg", "avg_pool2d",
                    ceil_mode=ceil_mode, exclusive=exclusive,
                    divisor_override=divisor_override)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = stride if stride is None or isinstance(stride, int) else stride[0]
    p = padding if isinstance(padding, int) else padding[0]
    return _pool_nd(x, k, s, p, 1, "max", "max_pool1d", ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = stride if stride is None or isinstance(stride, int) else stride[0]
    p = padding if isinstance(padding, int) else padding[0]
    return _pool_nd(x, k, s, p, 1, "avg", "avg_pool1d", ceil_mode=ceil_mode,
                    exclusive=exclusive)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    return _pool_nd(x, kernel_size, stride, padding, 3, "max", "max_pool3d",
                    ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg", "avg_pool3d",
                    ceil_mode=ceil_mode, exclusive=exclusive,
                    divisor_override=divisor_override)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    oh, ow = _tup(output_size, 2)

    def impl(a):
        n, c, h, w = a.shape
        if oh is not None and h % oh == 0 and w % ow == 0:
            out = a.reshape(n, c, oh, h // oh, ow, w // ow)
            return out.mean(axis=(3, 5))
        rows = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh)))
                for i in range(oh)]
        cols = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow)))
                for j in range(ow)]
        return jnp.stack([
            jnp.stack([a[:, :, r0:r1, c0:c1].mean(axis=(2, 3))
                       for (c0, c1) in cols], axis=-1)
            for (r0, r1) in rows], axis=-2)
    return apply_op("adaptive_avg_pool2d", impl, (x,), {})


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    oh, ow = _tup(output_size, 2)

    def impl(a):
        n, c, h, w = a.shape
        if h % oh == 0 and w % ow == 0:
            out = a.reshape(n, c, oh, h // oh, ow, w // ow)
            return out.max(axis=(3, 5))
        rows = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh)))
                for i in range(oh)]
        cols = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow)))
                for j in range(ow)]
        return jnp.stack([
            jnp.stack([a[:, :, r0:r1, c0:c1].max(axis=(2, 3))
                       for (c0, c1) in cols], axis=-1)
            for (r0, r1) in rows], axis=-2)
    return apply_op("adaptive_max_pool2d", impl, (x,), {})


def adaptive_avg_pool1d(x, output_size):
    o = output_size if isinstance(output_size, int) else output_size[0]

    def impl(a):
        n, c, l = a.shape
        if l % o == 0:
            return a.reshape(n, c, o, l // o).mean(axis=3)
        bounds = [(int(np.floor(i * l / o)), int(np.ceil((i + 1) * l / o)))
                  for i in range(o)]
        return jnp.stack([a[:, :, b0:b1].mean(axis=2) for (b0, b1) in bounds],
                         axis=-1)
    return apply_op("adaptive_avg_pool1d", impl, (x,), {})


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    od, oh, ow = _tup(output_size, 3)

    def impl(a):
        n, c, d, h, w = a.shape
        if d % od == 0 and h % oh == 0 and w % ow == 0:
            out = a.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
            return out.mean(axis=(3, 5, 7))
        ds = [(int(np.floor(i * d / od)), int(np.ceil((i + 1) * d / od)))
              for i in range(od)]
        hs = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh)))
              for i in range(oh)]
        ws = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow)))
              for j in range(ow)]
        return jnp.stack([
            jnp.stack([
                jnp.stack([a[:, :, d0:d1, h0:h1, w0:w1].mean(axis=(2, 3, 4))
                           for (w0, w1) in ws], axis=-1)
                for (h0, h1) in hs], axis=-2)
            for (d0, d1) in ds], axis=-3)
    return apply_op("adaptive_avg_pool3d", impl, (x,), {})


def adaptive_max_pool1d(x, output_size, return_mask=False):
    o = output_size if isinstance(output_size, int) else output_size[0]

    def impl(a):
        n, c, l = a.shape
        if l % o == 0:
            return a.reshape(n, c, o, l // o).max(axis=3)
        bounds = [(int(np.floor(i * l / o)), int(np.ceil((i + 1) * l / o)))
                  for i in range(o)]
        return jnp.stack([a[:, :, b0:b1].max(axis=2) for (b0, b1) in bounds],
                         axis=-1)
    out = apply_op("adaptive_max_pool1d", impl, (x,), {})
    if return_mask:
        def mask_impl(a):
            n, c, l = a.shape
            bounds = [(int(np.floor(i * l / o)), int(np.ceil((i + 1) * l / o)))
                      for i in range(o)]
            return jnp.stack([a[:, :, b0:b1].argmax(axis=2) + b0
                              for (b0, b1) in bounds], axis=-1).astype(jnp.int32)
        return out, apply_op("adaptive_max_pool1d_mask", mask_impl, (x,), {},
                             differentiable=False)
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW"):
    od, oh, ow = _tup(output_size, 3)

    def impl(a):
        n, c, d, h, w = a.shape
        if d % od == 0 and h % oh == 0 and w % ow == 0:
            out = a.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
            return out.max(axis=(3, 5, 7))
        ds = [(int(np.floor(i * d / od)), int(np.ceil((i + 1) * d / od)))
              for i in range(od)]
        hs = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh)))
              for i in range(oh)]
        ws = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow)))
              for j in range(ow)]
        return jnp.stack([
            jnp.stack([
                jnp.stack([a[:, :, d0:d1, h0:h1, w0:w1].max(axis=(2, 3, 4))
                           for (w0, w1) in ws], axis=-1)
                for (h0, h1) in hs], axis=-2)
            for (d0, d1) in ds], axis=-3)
    out = apply_op("adaptive_max_pool3d", impl, (x,), {})
    if return_mask:
        def mask_impl(a):
            n, c, d, h, w = a.shape
            ds = [(int(np.floor(i * d / od)), int(np.ceil((i + 1) * d / od)))
                  for i in range(od)]
            hs = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh)))
                  for i in range(oh)]
            ws = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow)))
                  for j in range(ow)]

            def region_idx(d0, d1, h0, h1, w0, w1):
                r = a[:, :, d0:d1, h0:h1, w0:w1].reshape(n, c, -1)
                flat = jnp.argmax(r, axis=-1)
                rd, rh, rw = d1 - d0, h1 - h0, w1 - w0
                di = flat // (rh * rw) + d0
                hi = (flat // rw) % rh + h0
                wi = flat % rw + w0
                return (di * h + hi) * w + wi
            return jnp.stack([
                jnp.stack([
                    jnp.stack([region_idx(d0, d1, h0, h1, w0, w1)
                               for (w0, w1) in ws], axis=-1)
                    for (h0, h1) in hs], axis=-2)
                for (d0, d1) in ds], axis=-3).astype(jnp.int32)
        return out, apply_op("adaptive_max_pool3d_mask", mask_impl, (x,), {},
                             differentiable=False)
    return out


def _lp_pool_nd(x, norm_type, kernel_size, stride, padding, ceil_mode,
                spatial, name):
    """L-p norm pooling: (sum |x|^p)^(1/p) over windows (reference
    lp_pool kernels)."""
    p = float(norm_type)
    if stride is None:
        stride = kernel_size

    def impl(a):
        powed = jnp.abs(a) ** p
        k, s, sp_pads = _resolve_pads(kernel_size, stride, padding, ceil_mode,
                                      a.shape[2:])
        summed = jax.lax.reduce_window(
            powed, 0.0, jax.lax.add, (1, 1) + k, (1, 1) + s,
            [(0, 0), (0, 0)] + sp_pads)
        return summed ** (1.0 / p)
    return apply_op(name, impl, (x,), {})


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL"):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = stride if stride is None or isinstance(stride, int) else stride[0]
    pd = padding if isinstance(padding, int) else padding[0]
    return _lp_pool_nd(x, norm_type, k, s, pd, ceil_mode, 1, "lp_pool1d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW"):
    return _lp_pool_nd(x, norm_type, kernel_size, stride, padding, ceil_mode,
                       2, "lp_pool2d")


def _max_unpool_nd(x, indices, spatial, kernel_size, stride=None, padding=0,
                   output_size=None, name="max_unpool"):
    """Scatter pooled values back to pre-pool positions using the flat
    spatial indices produced by max_pool*(return_mask=True) (reference
    max_unpool kernels)."""
    if stride is None:
        stride = kernel_size

    def impl(a, idx):
        lead = a.shape[:2]
        in_sizes = a.shape[2:]
        if output_size is not None:
            out_sizes = tuple(output_size)[-spatial:]
        else:
            k = _tup(kernel_size, spatial)
            s = _tup(stride, spatial)
            p = _tup(padding, spatial)
            out_sizes = tuple((in_sizes[i] - 1) * s[i] - 2 * p[i] + k[i]
                              for i in range(spatial))
        flat_out = int(np.prod(out_sizes))
        nflat = int(np.prod(lead))
        av = a.reshape(nflat, -1)
        iv = idx.reshape(nflat, -1).astype(jnp.int32)
        out = jnp.zeros((nflat, flat_out), a.dtype)
        out = jax.vmap(lambda o, i, v: o.at[i].set(v))(out, iv, av)
        return out.reshape(lead + out_sizes)
    return apply_op(name, impl, (x, indices), {})


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None):
    return _max_unpool_nd(x, indices, 1, kernel_size, stride, padding,
                          output_size, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None):
    return _max_unpool_nd(x, indices, 2, kernel_size, stride, padding,
                          output_size, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None):
    return _max_unpool_nd(x, indices, 3, kernel_size, stride, padding,
                          output_size, "max_unpool3d")


def _fractional_starts(in_size, out_size, k, u):
    """Pseudo-random window starts for fractional pooling (Graham 2014,
    the reference's fractional_max_pool kernels): alpha = in/out steps,
    jittered by u in [0,1)."""
    alpha = (in_size - k) / max(out_size - 1, 1)
    starts = [int(np.floor(alpha * (i + u))) for i in range(out_size)]
    starts = [min(s, in_size - k) for s in starts]
    if out_size > 0:
        starts[0] = 0
    return starts


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False):
    oh, ow = _tup(output_size, 2)

    def impl(a):
        n, c, h, w = a.shape
        kh = kernel_size if isinstance(kernel_size, int) else \
            (kernel_size[0] if kernel_size else h // oh + 1)
        kw = kernel_size if isinstance(kernel_size, int) else \
            (kernel_size[1] if kernel_size else w // ow + 1)
        u = float(random_u) if random_u is not None else 0.5
        rs = _fractional_starts(h, oh, kh, u)
        cs = _fractional_starts(w, ow, kw, u)
        return jnp.stack([
            jnp.stack([a[:, :, r:r + kh, cc:cc + kw].max(axis=(2, 3))
                       for cc in cs], axis=-1)
            for r in rs], axis=-2)
    out = apply_op("fractional_max_pool2d", impl, (x,), {})
    if return_mask:
        def mask_impl(a):
            n, c, h, w = a.shape
            kh = kernel_size if isinstance(kernel_size, int) else \
                (kernel_size[0] if kernel_size else h // oh + 1)
            kw = kernel_size if isinstance(kernel_size, int) else \
                (kernel_size[1] if kernel_size else w // ow + 1)
            u = float(random_u) if random_u is not None else 0.5
            rs = _fractional_starts(h, oh, kh, u)
            cs = _fractional_starts(w, ow, kw, u)

            def region_idx(r, cc):
                reg = a[:, :, r:r + kh, cc:cc + kw].reshape(n, c, -1)
                flat = jnp.argmax(reg, axis=-1)
                return (flat // kw + r) * w + (flat % kw + cc)
            return jnp.stack([
                jnp.stack([region_idx(r, cc) for cc in cs], axis=-1)
                for r in rs], axis=-2).astype(jnp.int32)
        return out, apply_op("fractional_max_pool2d_mask", mask_impl, (x,),
                             {}, differentiable=False)
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False):
    od, oh, ow = _tup(output_size, 3)

    def impl(a):
        n, c, d, h, w = a.shape
        if kernel_size is None:
            kd, kh, kw = d // od + 1, h // oh + 1, w // ow + 1
        elif isinstance(kernel_size, int):
            kd = kh = kw = kernel_size
        else:
            kd, kh, kw = kernel_size
        u = float(random_u) if random_u is not None else 0.5
        dsl = _fractional_starts(d, od, kd, u)
        rs = _fractional_starts(h, oh, kh, u)
        cs = _fractional_starts(w, ow, kw, u)
        return jnp.stack([
            jnp.stack([
                jnp.stack([a[:, :, dd:dd + kd, r:r + kh, cc:cc + kw]
                           .max(axis=(2, 3, 4)) for cc in cs], axis=-1)
                for r in rs], axis=-2)
            for dd in dsl], axis=-3)
    out = apply_op("fractional_max_pool3d", impl, (x,), {})
    if return_mask:
        def mask_impl(a):
            n, c, d, h, w = a.shape
            if kernel_size is None:
                kd, kh, kw = d // od + 1, h // oh + 1, w // ow + 1
            elif isinstance(kernel_size, int):
                kd = kh = kw = kernel_size
            else:
                kd, kh, kw = kernel_size
            u = float(random_u) if random_u is not None else 0.5
            dsl = _fractional_starts(d, od, kd, u)
            rs = _fractional_starts(h, oh, kh, u)
            cs = _fractional_starts(w, ow, kw, u)

            def region_idx(dd, r, cc):
                reg = a[:, :, dd:dd + kd, r:r + kh, cc:cc + kw].reshape(
                    n, c, -1)
                flat = jnp.argmax(reg, axis=-1)
                di = flat // (kh * kw) + dd
                hi = (flat // kw) % kh + r
                wi = flat % kw + cc
                return (di * h + hi) * w + wi
            return jnp.stack([
                jnp.stack([
                    jnp.stack([region_idx(dd, r, cc) for cc in cs], axis=-1)
                    for r in rs], axis=-2)
                for dd in dsl], axis=-3).astype(jnp.int32)
        return out, apply_op("fractional_max_pool3d_mask", mask_impl, (x,),
                             {}, differentiable=False)
    return out
