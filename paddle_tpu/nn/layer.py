"""nn.Layer — module base class.

Reference: python/paddle/nn/layer/layers.py (Layer with hooks, state_dict,
train/eval, sublayer registry). Parameters are eager Tensors; the functional
bridge for jit/pjit lives in paddle_tpu.jit.functional_call (swap params for
traced arrays, run the same forward).
"""
import collections
import itertools

import numpy as np

_hook_counter = itertools.count()  # monotonic: removal never frees a key

from ..core.tensor import Tensor, Parameter
from ..core.dtypes import convert_dtype
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        # use object.__setattr__: our __setattr__ routes Tensors/Layers
        d = self.__dict__
        d["_parameters"] = collections.OrderedDict()
        d["_sub_layers"] = collections.OrderedDict()
        d["_buffers"] = collections.OrderedDict()
        d["_non_persistable_buffer_names"] = set()
        d["_forward_pre_hooks"] = collections.OrderedDict()
        d["_forward_post_hooks"] = collections.OrderedDict()
        d["training"] = True
        d["_dtype"] = convert_dtype(dtype)
        d["_name_scope"] = name_scope or self.__class__.__name__.lower()

    # -- construction ---------------------------------------------------
    def create_parameter(self, shape, dtype=None, attr=None, is_bias=False,
                         default_initializer=None):
        dtype = convert_dtype(dtype) or self._dtype
        init = None
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, trainable=True)
        if attr is not None and getattr(attr, "name", None):
            p.name = attr.name
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.trainable = False
            p.stop_gradient = True
        if attr is not None and getattr(attr, "learning_rate", None) is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None:
            # persistent-identity marker: the SOT replay may hold a strong
            # ref to a buffer (like a Parameter) — see _input_locator
            try:
                tensor._is_layer_buffer = True
            except AttributeError:
                pass
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute routing ---------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for store in (layers, buffers):
                if store is not None:
                    store.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for store in (params, buffers):
                if store is not None:
                    store.pop(name, None)
            layers[name] = value
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, None)
                    return
                raise TypeError(
                    f"cannot assign non-Parameter to parameter slot '{name}'; "
                    "use .set_value() to update in place")
            if buffers is not None and name in buffers:
                buffers[name] = value
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store_name in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(store_name)
            if store is not None and name in store:
                return store[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store_name in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(store_name)
            if store is not None and name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extras = (list(self._parameters) + list(self._sub_layers)
                  + list(self._buffers))
        return sorted(set(super().__dir__() + extras))

    # -- call protocol --------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def register_forward_pre_hook(self, hook):
        key = next(_hook_counter)
        self._forward_pre_hooks[key] = hook
        return HookRemoveHelper(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = next(_hook_counter)
        self._forward_post_hooks[key] = hook
        return HookRemoveHelper(self._forward_post_hooks, key)

    # -- traversal ------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{name}.{pname}" if name else pname, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{name}.{bname}" if name else bname, b)

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- modes ----------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # -- state ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self.named_sublayers(
                prefix=structured_name_prefix.rstrip("."), include_self=True):
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    dest[f"{name}.{bname}" if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src.data if isinstance(src, Tensor) else np.asarray(src)
                target.set_value(arr)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            from ..core.dtypes import is_floating
            dt = convert_dtype(dtype)
            for p in self.parameters():
                if is_floating(p.dtype):
                    p._data = p.data.astype(dt)
            for b in self.buffers():
                if hasattr(b, "data") and is_floating(b.dtype):
                    b._data = b.data.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}" if extra
                 else f"{self.__class__.__name__}("]
        for name, child in self._sub_layers.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        return "\n".join(lines) + "\n)" if len(lines) > 1 else lines[0] + ")"


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=None,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
