"""paddle.nn surface (reference: python/paddle/nn/__init__.py — ~150 layers)."""
from .layer import Layer, ParamAttr
from . import functional
from . import initializer
from . import quant
from .clip import (ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
                   clip_grad_norm_)

from .layers.common import (
    Identity, Linear, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Embedding, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    PixelShuffle, PixelUnshuffle, Pad1D, Pad2D, Pad3D, CosineSimilarity,
    PairwiseDistance, Unfold, ZeroPad2D, Bilinear, Fold,
)
from .layers.conv import Conv1D, Conv2D, Conv3D, Conv2DTranspose
from .layers.norm import (
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D,
    InstanceNorm3D, LocalResponseNorm, SpectralNorm)
from .layers.pooling import (
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveMaxPool2D,
)
from .layers.activation import (
    ReLU, ReLU6, GELU, SiLU, Swish, Mish, Sigmoid, Tanh, Softmax, LogSoftmax,
    LeakyReLU, ELU, SELU, CELU, Softplus, Softshrink, Softsign, Hardshrink,
    Hardtanh, Hardsigmoid, Hardswish, Tanhshrink, ThresholdedReLU, Maxout,
    GLU, PReLU, RReLU, LogSigmoid,
)
from .layers.container import Sequential, LayerList, ParameterList, LayerDict
from .layers.loss import (
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, HuberLoss, KLDivLoss, MarginRankingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, CTCLoss)
from .layers.transformer import (
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layers.rnn import (LSTM, GRU, SimpleRNN, LSTMCell, GRUCell,
                         RNNCellBase, SimpleRNNCell, RNN, BiRNN)
from .layers.conv import Conv1DTranspose, Conv3DTranspose
from .layers.decode import BeamSearchDecoder, dynamic_decode
from .layers.extra_layers import (
    Silu, Softmax2D, ChannelShuffle, Unflatten, FeatureAlphaDropout,
    ParameterDict, ZeroPad1D, ZeroPad3D,
    AdaptiveAvgPool3D, AdaptiveMaxPool1D, AdaptiveMaxPool3D,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    FractionalMaxPool2D, FractionalMaxPool3D, LPPool1D, LPPool2D,
    PoissonNLLLoss, SoftMarginLoss, MultiLabelSoftMarginLoss,
    MultiMarginLoss, HingeEmbeddingLoss, GaussianNLLLoss,
    TripletMarginWithDistanceLoss, RNNTLoss, HSigmoidLoss,
    AdaptiveLogSoftmaxWithLoss,
)
