"""Weight initializers (reference: python/paddle/nn/initializer/*). Each
initializer is a callable (shape, dtype) -> jax array, consuming the global
PRNG chain."""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as _random
from ..core.dtypes import convert_dtype


def _fan_in_out(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # paddle convention: linear weights are [in, out]; convs are [out, in, kh, kw]
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        fan_out = shape[0] * receptive
        fan_in = shape[1] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        out = jax.random.normal(_random.next_key(), shape, jnp.float32)
        return (out * self.std + self.mean).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        out = jax.random.truncated_normal(_random.next_key(), self.a, self.b,
                                          shape, jnp.float32)
        return (out * self.std + self.mean).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        return jax.random.uniform(_random.next_key(), shape, jnp.float32,
                                  self.low, self.high).astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in, self.negative_slope = fan_in, negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in, self.negative_slope = fan_in, negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value.data if hasattr(self.value, "data") else np.asarray(self.value)
        return jnp.asarray(v, dtype=convert_dtype(dtype)).reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        return (jax.nn.initializers.orthogonal(self.gain)(
            _random.next_key(), shape, jnp.float32)).astype(dt)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        center = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(mins):
                out[(g * (oc // self.groups) + i, i) + center] = 1.0
        return jnp.asarray(out, dtype=convert_dtype(dtype))


# paddle-style short aliases
constant_ = Constant
normal_ = Normal
uniform_ = Uniform
xavier_normal_ = XavierNormal
xavier_uniform_ = XavierUniform
kaiming_normal_ = KaimingNormal
kaiming_uniform_ = KaimingUniform
set_global_initializer = None  # placeholder for parity; rarely used


def calculate_gain(nonlinearity, param=None):
    """Recommended init gain per nonlinearity (reference calculate_gain)."""
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0), "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity in gains:
        return gains[nonlinearity]
    raise ValueError(f"unsupported nonlinearity: {nonlinearity}")


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    nn.initializer.Bilinear): weight[c_in, c_out, kh, kw] gets the bilinear
    interpolation stencil."""

    def __call__(self, shape, dtype):
        import numpy as np
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        _, _, kh, kw = shape
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = np.ogrid[:kh, :kw]
        filt = (1 - abs(og[0] / f_h - c_h)) * (1 - abs(og[1] / f_w - c_w))
        w = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            for j in range(shape[1]):
                w[i, j] = filt
        import jax.numpy as jnp
        return jnp.asarray(w, dtype)


