"""Conv layers (reference: python/paddle/nn/layer/conv.py). Weight layout
[out_channels, in_channels/groups, *kernel] matching paddle."""
from ..layer import Layer
from .. import functional as F
from .. import initializer as I


def _ntuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, spatial,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, spatial)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self._spatial = spatial
        fan_in = in_channels // groups
        for k in self.kernel_size:
            fan_in *= k
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *self.kernel_size],
            attr=weight_attr, default_initializer=I.KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                              is_bias=True)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={list(self.kernel_size)}, stride={self.stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2DTranspose(Layer):
    """Weight layout [in_channels, out_channels/groups, kh, kw] (paddle)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, 2)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        fan_in = in_channels
        for k in self.kernel_size:
            fan_in *= k
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *self.kernel_size],
            attr=weight_attr, default_initializer=I.KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups,
                                  output_size, self.data_format)


class Conv1DTranspose(Layer):
    """Weight layout [in_channels, out_channels/groups, k] (paddle)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size = _ntuple(kernel_size, 1)
        self.stride, self.padding = stride, padding
        self.output_padding, self.dilation, self.groups = \
            output_padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * self.kernel_size[0]
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *self.kernel_size],
            attr=weight_attr, default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups, output_size,
                                  self.data_format)


class Conv3DTranspose(Layer):
    """Weight layout [in_channels, out_channels/groups, kd, kh, kw]."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size = _ntuple(kernel_size, 3)
        self.stride, self.padding = stride, padding
        self.output_padding, self.dilation, self.groups = \
            output_padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels
        for k in self.kernel_size:
            fan_in *= k
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *self.kernel_size],
            attr=weight_attr, default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups, output_size,
                                  self.data_format)
