"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py). The scan over
time is lax.scan — the XLA-native recurrence (compiles to a single fused loop
on TPU instead of per-step kernel launches)."""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ..layer import Layer
from .. import initializer as I


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size],
                                             attr=bias_ih_attr,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size],
                                             attr=bias_hh_attr,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            from ... import ops
            b = inputs.shape[0]
            states = (ops.zeros([b, self.hidden_size]),
                      ops.zeros([b, self.hidden_size]))
        h, c = states

        def impl(x, h_, c_, wih, whh, bih, bhh):
            return _lstm_step(x, h_, c_, wih, whh, bih, bhh)
        h2, c2 = apply_op("lstm_cell", impl,
                          (inputs, h, c, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh), {})
        return h2, (h2, c2)


def _lstm_step(x, h, c, wih, whh, bih, bhh):
    gates = x @ wih.T + h @ whh.T + bih + bhh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _gru_step(x, h, wih, whh, bih, bhh):
    gi = x @ wih.T + bih
    gh = h @ whh.T + bhh
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return (1 - z) * n + z * h


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size],
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size],
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            from ... import ops
            states = ops.zeros([inputs.shape[0], self.hidden_size])

        def impl(x, h, wih, whh, bih, bhh):
            return _gru_step(x, h, wih, whh, bih, bhh)
        h2 = apply_op("gru_cell", impl,
                      (inputs, states, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh), {})
        return h2, h2


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrence via lax.scan."""

    MODE = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[self.MODE]
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 else hidden_size * self.num_directions
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                self.add_parameter("weight_ih" + sfx, self.create_parameter(
                    [gate_mult * hidden_size, in_sz], default_initializer=u))
                self.add_parameter("weight_hh" + sfx, self.create_parameter(
                    [gate_mult * hidden_size, hidden_size], default_initializer=u))
                self.add_parameter("bias_ih" + sfx, self.create_parameter(
                    [gate_mult * hidden_size], default_initializer=u))
                self.add_parameter("bias_hh" + sfx, self.create_parameter(
                    [gate_mult * hidden_size], default_initializer=u))

    def _step_fn(self):
        if self.MODE == "LSTM":
            return _lstm_step
        if self.MODE == "GRU":
            return _gru_step
        act = jnp.tanh if self.MODE == "RNN_TANH" else jax.nn.relu
        def step(x, h, wih, whh, bih, bhh):
            return act(x @ wih.T + h @ whh.T + bih + bhh)
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.MODE == "LSTM"
        step = self._step_fn()
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        params = []
        for layer in range(nl):
            for d in range(nd):
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                params.extend([
                    self._parameters["weight_ih" + sfx],
                    self._parameters["weight_hh" + sfx],
                    self._parameters["bias_ih" + sfx],
                    self._parameters["bias_hh" + sfx]])
        time_major = self.time_major
        has_init = initial_states is not None
        has_len = sequence_length is not None
        extra = []
        if has_init:
            extra.extend(initial_states if is_lstm else [initial_states])
        if has_len:
            extra.append(sequence_length)

        def impl(x, *flat):
            flat_params = flat[: 4 * nl * nd]
            rest = list(flat[4 * nl * nd:])
            h0_all = c0_all = seq_len = None
            if has_init:
                h0_all = rest.pop(0)
                if is_lstm:
                    c0_all = rest.pop(0)
            if has_len:
                seq_len = rest.pop(0)
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # -> [T, B, C]
            t_len, b = x.shape[0], x.shape[1]
            steps_fwd = jnp.arange(t_len)
            h_outs, c_outs = [], []
            inp = x
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    li = layer * nd + d
                    wih, whh, bih, bhh = flat_params[li * 4: li * 4 + 4]
                    seq = jnp.flip(inp, axis=0) if d == 1 else inp
                    # valid-step mask: for the reverse direction the flipped
                    # sequence has pad steps FIRST, so valid is t >= T - len
                    if seq_len is not None:
                        if d == 1:
                            valid = steps_fwd[:, None] >= (t_len - seq_len)[None, :]
                        else:
                            valid = steps_fwd[:, None] < seq_len[None, :]
                        valid = valid[..., None].astype(x.dtype)  # [T, B, 1]
                    else:
                        valid = None
                    h0 = h0_all[li] if h0_all is not None else jnp.zeros((b, hs), x.dtype)
                    if is_lstm:
                        c0 = c0_all[li] if c0_all is not None else jnp.zeros((b, hs), x.dtype)

                        def body(carry, xt_v):
                            h_, c_ = carry
                            xt, v = xt_v
                            h2, c2 = step(xt, h_, c_, wih, whh, bih, bhh)
                            if v is not None:
                                h2 = v * h2 + (1 - v) * h_
                                c2 = v * c2 + (1 - v) * c_
                            return (h2, c2), h2
                        if valid is None:
                            (hT, cT), outs = jax.lax.scan(
                                lambda c, xt: body(c, (xt, None)), (h0, c0), seq)
                        else:
                            (hT, cT), outs = jax.lax.scan(body, (h0, c0), (seq, valid))
                        c_outs.append(cT)
                    else:
                        def body(carry, xt_v):
                            xt, v = xt_v
                            h2 = step(xt, carry, wih, whh, bih, bhh)
                            if v is not None:
                                h2 = v * h2 + (1 - v) * carry
                            return h2, h2
                        if valid is None:
                            hT, outs = jax.lax.scan(
                                lambda c, xt: body(c, (xt, None)), h0, seq)
                        else:
                            hT, outs = jax.lax.scan(body, h0, (seq, valid))
                    h_outs.append(hT)
                    if d == 1:
                        outs = jnp.flip(outs, axis=0)
                    dir_outs.append(outs)
                inp = jnp.concatenate(dir_outs, axis=-1) if nd == 2 else dir_outs[0]
            out = inp if time_major else jnp.swapaxes(inp, 0, 1)
            h_stack = jnp.stack(h_outs)
            if is_lstm:
                return out, h_stack, jnp.stack(c_outs)
            return out, h_stack

        res = apply_op(self.MODE.lower(), impl, (inputs, *params, *extra), {})
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class RNNCellBase(Layer):
    """Cell-protocol base (reference RNNCellBase): a cell maps
    (input [B, C], states) -> (output, new_states) and exposes
    get_initial_states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        hs = getattr(self, "hidden_size", None)
        from ... import ops
        if getattr(self, "MODE", "") == "LSTM" or isinstance(self, LSTMCell):
            return (ops.full([b, hs], init_value),
                    ops.full([b, hs], init_value))
        return ops.full([b, hs], init_value)

    @property
    def state_shape(self):
        return (self.hidden_size,)


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference SimpleRNNCell)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, default_initializer=u)

    def forward(self, inputs, states=None):
        from ... import ops
        if states is None:
            states = ops.zeros([inputs.shape[0], self.hidden_size])
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def impl(x, h, wih, whh, bih, bhh):
            return act(x @ wih.T + bih + h @ whh.T + bhh)
        h2 = apply_op("simple_rnn_cell", impl,
                      (inputs, states, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh), {})
        return h2, h2


class RNN(Layer):
    """Wrap any cell into a recurrence over time (reference RNN wrapper).
    Dygraph runs the Python loop; under to_static the loop unrolls at trace
    time (fixed T), which XLA then schedules — the LSTM/GRU classes use the
    fused lax.scan path instead."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        t_axis = 0 if self.time_major else 1
        t_len = inputs.shape[t_axis]
        states = initial_states
        if states is None:
            states = self.cell.get_initial_states(
                inputs, batch_dim_idx=1 if self.time_major else 0)
        steps = range(t_len - 1, -1, -1) if self.is_reverse else range(t_len)
        outs = [None] * t_len
        for t in steps:
            xt = inputs[:, t] if t_axis == 1 else inputs[t]
            out, states = self.cell(xt, states)
            outs[t] = out
        stacked = ops.stack(outs, axis=t_axis)
        return stacked, states


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        s_fw = s_bw = None
        if initial_states is not None:
            s_fw, s_bw = initial_states
        o_fw, s_fw = self.rnn_fw(inputs, s_fw, sequence_length)
        o_bw, s_bw = self.rnn_bw(inputs, s_bw, sequence_length)
        return ops.concat([o_fw, o_bw], axis=-1), (s_fw, s_bw)


# LSTMCell/GRUCell predate RNNCellBase in this module; give them the cell
# protocol so RNN/BiRNN/BeamSearchDecoder accept them
LSTMCell.get_initial_states = RNNCellBase.get_initial_states
GRUCell.get_initial_states = RNNCellBase.get_initial_states
