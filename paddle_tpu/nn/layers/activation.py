"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from ..layer import Layer
from .. import functional as F
from .. import initializer as I


def _simple(name, fn, **fixed):
    class _Act(Layer):
        def __init__(self, name=None, **kwargs):  # `name` is paddle API parity
            super().__init__()
            self._kwargs = {**fixed, **kwargs}
            for k, v in self._kwargs.items():
                setattr(self, k, v)

        def forward(self, x):
            return fn(x, **self._kwargs)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _simple("ReLU", lambda x: F.relu(x))
ReLU6 = _simple("ReLU6", lambda x: F.relu6(x))
Sigmoid = _simple("Sigmoid", lambda x: F.sigmoid(x))
Tanh = _simple("Tanh", lambda x: F.tanh(x))
SiLU = _simple("SiLU", lambda x: F.silu(x))
Swish = _simple("Swish", lambda x: F.silu(x))
Mish = _simple("Mish", lambda x: F.mish(x))
Softsign = _simple("Softsign", lambda x: F.softsign(x))
Tanhshrink = _simple("Tanhshrink", lambda x: F.tanhshrink(x))
Hardswish = _simple("Hardswish", lambda x: F.hardswish(x))
LogSigmoid = _simple("LogSigmoid", lambda x: F.log_sigmoid(x))


class GELU(Layer):
    def __init__(self, approximate=False):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardsigmoid(Layer):
    def __init__(self, slope=0.1666667, offset=0.5):
        super().__init__()
        self.slope, self.offset = slope, offset

    def forward(self, x):
        return F.hardsigmoid(x, self.slope, self.offset)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class Maxout(Layer):
    def __init__(self, groups, axis=1):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW"):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=0.3333333):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
