"""Seq2seq decoding (reference: python/paddle/nn/decode.py —
BeamSearchDecoder + dynamic_decode). TPU-native notes: the per-step state is
kept as stacked beam tensors [B, beam, ...] so every step is batched matmuls;
the ancestry backtrace is F.gather_tree (a lax.scan)."""
import numpy as np

from ..layer import Layer
from .. import functional as F


class Decoder:
    """Decoding protocol: initialize / step / finalize (reference Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam search over a cell (reference BeamSearchDecoder): expands each
    batch item to `beam_size` hypotheses, scores with log-softmax of the
    output layer, and keeps the top beams each step."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] by repeating each row (reference
        helper of the same name)."""
        from ... import ops
        reps = [1] * (x.ndim + 1)
        reps[1] = beam_size
        return ops.tile(x.unsqueeze(1), reps).reshape([-1, *x.shape[1:]])

    def _merge(self, x):
        return x.reshape([-1, *x.shape[2:]])

    def _split(self, x, batch):
        return x.reshape([batch, self.beam_size, *x.shape[1:]])

    def initialize(self, inits):
        from ... import ops
        cell_states = inits
        some = cell_states[0] if isinstance(cell_states, (list, tuple)) \
            else cell_states
        batch = some.shape[0]
        exp = lambda t: self.tile_beam_merge_with_batch(t, self.beam_size)
        if isinstance(cell_states, (list, tuple)):
            cell_states = type(cell_states)(exp(s) for s in cell_states)
        else:
            cell_states = exp(cell_states)
        ids = ops.full([batch, self.beam_size], self.start_token,
                       dtype="int64")
        # only beam 0 is live initially (others at -inf so the first top-k
        # doesn't pick duplicate roots)
        neg = np.full((batch, self.beam_size), -1e9, np.float32)
        neg[:, 0] = 0.0
        scores = ops.assign(neg)
        finished = ops.zeros([batch, self.beam_size], dtype="bool")
        return ids, (cell_states, scores, finished)

    def step(self, time, inputs, states):
        from ... import ops
        cell_states, scores, finished = states
        batch = scores.shape[0]
        tok = inputs.reshape([-1])
        emb = self.embedding_fn(tok) if self.embedding_fn is not None else tok
        cell_out, new_states = self.cell(emb, cell_states)
        logits = self.output_fn(cell_out) if self.output_fn is not None \
            else cell_out
        logp = F.log_softmax(logits, axis=-1)              # [B*beam, V]
        v = logp.shape[-1]
        logp = self._split(logp, batch)                    # [B, beam, V]
        # finished beams only extend with end_token at score 0
        fin = finished.unsqueeze(-1).astype("float32")
        mask = np.full((1, 1, v), -1e9, np.float32)
        mask[0, 0, self.end_token] = 0.0
        logp = logp * (1 - fin) + ops.assign(mask) * fin
        total = scores.unsqueeze(-1) + logp                # [B, beam, V]
        flat = total.reshape([batch, -1])
        top_scores, top_idx = flat.topk(self.beam_size, axis=-1)
        parent = (top_idx // v).astype("int64")            # [B, beam]
        token = (top_idx % v).astype("int64")
        # gather parent cell states
        offs = ops.arange(0, batch, dtype="int64").unsqueeze(-1) * self.beam_size
        flat_parent = (parent + offs).reshape([-1])

        def pick(s):
            return s[flat_parent]
        if isinstance(new_states, (list, tuple)):
            new_states = type(new_states)(pick(s) for s in new_states)
        else:
            new_states = pick(new_states)
        new_finished = finished.reshape([batch * self.beam_size])[
            flat_parent].reshape([batch, self.beam_size])
        new_finished = ops.logical_or(
            new_finished, ops.equal(token, ops.full_like(token, self.end_token)))
        return (token, parent, top_scores), \
            (new_states, top_scores, new_finished), token, new_finished


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run a Decoder until all beams finish or max_step_num (reference
    dynamic_decode). Returns (ids [B, beam, T] backtraced, scores)."""
    from ... import ops
    inputs, states = decoder.initialize(inits)
    step_tokens, step_parents = [], []
    scores = None
    max_steps = max_step_num or 32
    for t in range(max_steps):
        (token, parent, scores), states, next_inputs, finished = \
            decoder.step(t, inputs, states)
        step_tokens.append(token)
        step_parents.append(parent)
        inputs = next_inputs
        if bool(finished.all()):
            break
    ids = ops.stack(step_tokens, axis=0)       # [T, B, beam]
    parents = ops.stack(step_parents, axis=0)
    traced = F.gather_tree(ids, parents)       # [T, B, beam]
    if not output_time_major:
        traced = traced.transpose([1, 2, 0])   # [B, beam, T]
    out = (traced, scores)
    if return_length:
        seq_len = (traced != decoder.end_token).astype("int64").sum(-1)
        out = out + (seq_len,)
    return out
