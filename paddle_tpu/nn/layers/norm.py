"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
import numpy as np

from ...core.tensor import Tensor
from ..layer import Layer
from .. import functional as F
from .. import initializer as I


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_format=self.data_format,
            use_global_stats=self.use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync across the data-parallel mesh axis happens
    inside pjit (XLA inserts the cross-replica mean) — the eager single-host
    path is plain batch_norm (reference: python/paddle/nn/layer/norm.py
    SyncBatchNorm + c_sync_calc kernels collapse into the compiler)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.num_features, layer.momentum,
                                layer.epsilon, data_format=layer.data_format)
            new.set_state_dict(dict(layer.state_dict()))
            return new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor
    (reference nn/layer/norm.py SpectralNorm): forward returns
    W / sigma(W), updating the u/v estimates in train mode."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        import numpy as _np
        self._dim = dim
        self._power_iters = power_iters
        self._eps = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter([h], dtype=dtype)
        self.weight_v = self.create_parameter([w], dtype=dtype)
        with __import__("paddle_tpu").no_grad():
            self.weight_u.set_value(
                _np.random.default_rng(0).standard_normal(h).astype(dtype))
            self.weight_v.set_value(
                _np.random.default_rng(1).standard_normal(w).astype(dtype))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp
        from ...core.dispatch import apply_op
        dim, eps, iters = self._dim, self._eps, self._power_iters
        training = self.training
        u0, v0 = self.weight_u.data, self.weight_v.data

        def impl(w):
            m = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            # iterate in eval too (the estimate must exist even with fresh
            # u/v); only the buffer write-back below is train-gated
            for _ in range(iters):
                v = m.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = m @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ (m @ v)
            return w / sigma, u, v

        out, u_new, v_new = apply_op("spectral_norm", impl, (weight,), {})
        if training:
            self.weight_u.data = u_new.data
            self.weight_v.data = v_new.data
        return out
