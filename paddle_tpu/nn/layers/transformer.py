"""Transformer layers (reference: python/paddle/nn/layer/transformer.py:
MultiHeadAttention, TransformerEncoder/Decoder). Attention dispatches through
F.scaled_dot_product_attention so the Pallas flash kernel serves it on TPU."""
from ...core.tensor import Tensor
from ..layer import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList
from .. import functional as F


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        b, s_q = query.shape[0], query.shape[1]
        q = self.q_proj(query).reshape([b, s_q, self.num_heads, self.head_dim])
        k = self.k_proj(key).reshape([b, key.shape[1], self.num_heads, self.head_dim])
        v = self.v_proj(value).reshape([b, value.shape[1], self.num_heads, self.head_dim])
        if cache is not None:
            k = _concat_cache(cache, "k", k)
            v = _concat_cache(cache, "v", v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        out = out.reshape([b, s_q, self.embed_dim])
        return self.out_proj(out)


def _concat_cache(cache, name, new):
    from ... import ops
    prev = cache.get(name)
    if prev is not None:
        new = ops.concat([prev, new], axis=1)
    cache[name] = new
    return new


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] +
                                [copy.deepcopy(encoder_layer)
                                 for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, attn_mask=tgt_mask, cache=cache)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([decoder_layer] +
                                [copy.deepcopy(decoder_layer)
                                 for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def gen_cache(self, memory=None):
        """Per-layer incremental-decoding caches (reference:
        TransformerDecoder.gen_cache, python/paddle/nn/layer/transformer.py)."""
        return [{} for _ in range(self.num_layers)]

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        if cache is not None and isinstance(cache, dict):
            raise TypeError(
                "cache must be a per-layer list (use decoder.gen_cache()); "
                "a single dict would share one k/v cache across all layers")
        out = tgt
        for i, layer in enumerate(self.layers):
            out = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask,
                        cache=None if cache is None else cache[i])
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False):
        super().__init__()
        enc_layer = TransformerEncoderLayer(
            d_model, nhead, dim_feedforward, dropout, activation,
            attn_dropout, act_dropout, normalize_before)
        dec_layer = TransformerDecoderLayer(
            d_model, nhead, dim_feedforward, dropout, activation,
            attn_dropout, act_dropout, normalize_before)
        enc_norm = LayerNorm(d_model) if normalize_before else None
        dec_norm = LayerNorm(d_model) if normalize_before else None
        self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import numpy as np
        import jax.numpy as jnp
        mask = np.triu(np.full((length, length), -np.inf, dtype=np.float32), k=1)
        return Tensor(jnp.asarray(mask))
