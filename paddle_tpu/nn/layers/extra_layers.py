"""Layer-class completion batch (reference: python/paddle/nn/layer/ —
pooling.py, loss.py, common.py, activation.py). Thin class wrappers over the
functional surface, matching the reference constructor signatures."""
import numpy as np

from ..layer import Layer
from .. import functional as F
from .. import initializer as I


# -- activations / misc ----------------------------------------------------
class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


class Softmax2D(Layer):
    """Softmax over channel dim of NCHW input (reference Softmax2D)."""

    def forward(self, x):
        assert x.ndim in (3, 4)
        return F.softmax(x, axis=-3)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW"):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ... import ops
        return ops.unflatten(x, self.axis, self.shape)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, self.training)


class ParameterDict(Layer):
    """Named parameter container (reference ParameterDict)."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for k, v in (parameters.items() if isinstance(parameters, dict)
                         else parameters):
                self.add_parameter(k, v)

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, param):
        self.add_parameter(key, param)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def __contains__(self, key):
        return key in self._parameters

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        for k, v in (parameters.items() if isinstance(parameters, dict)
                     else parameters):
            self.add_parameter(k, v)


# -- padding ---------------------------------------------------------------
class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = [padding, padding] if isinstance(padding, int) \
            else list(padding)
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.data_format)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = [padding] * 6 if isinstance(padding, int) \
            else list(padding)
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.data_format)


# -- pooling ---------------------------------------------------------------
class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size, self.data_format = output_size, data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size, self.return_mask = output_size, return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size, self.return_mask = output_size, return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type, self.kernel_size = norm_type, kernel_size
        self.stride, self.padding, self.ceil_mode = stride, padding, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type, self.kernel_size = norm_type, kernel_size
        self.stride, self.padding, self.ceil_mode = stride, padding, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


# -- losses ----------------------------------------------------------------
class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.fastemit_lambda = blank, fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classifier head (reference HSigmoidLoss):
    holds the inner-node weight table [num_classes-1, feature_size]."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        std = 1.0 / np.sqrt(feature_size)
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr,
            default_initializer=I.Uniform(-std, std))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [num_classes - 1, 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax head (reference AdaptiveLogSoftmaxWithLoss):
    shortlist head + per-cluster down-projected tails (div_value shrink)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.n_classes = n_classes
        shortlist = self.cutoffs[0]
        n_clusters = len(self.cutoffs) - 1
        self.head_weight = self.create_parameter(
            [in_features, shortlist + n_clusters],
            default_initializer=I.XavierNormal())
        self.head_bias = self.create_parameter(
            [shortlist + n_clusters], is_bias=True) if head_bias else None
        self.tail_weights = []
        for ci in range(n_clusters):
            lo, hi = self.cutoffs[ci], self.cutoffs[ci + 1]
            proj_dim = max(1, int(in_features / (div_value ** (ci + 1))))
            proj = self.create_parameter([in_features, proj_dim],
                                         default_initializer=I.XavierNormal())
            w = self.create_parameter([proj_dim, hi - lo],
                                      default_initializer=I.XavierNormal())
            self.add_parameter(f"tail_proj_{ci}", proj)
            self.add_parameter(f"tail_w_{ci}", w)
            self.tail_weights.append((proj, w))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], self.head_bias)
