"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from ..layer import Layer
from .. import functional as F
from .. import initializer as I


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """weight shape [in_features, out_features] (paddle convention — maps
    directly to x @ W on the MXU with no transpose)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             data_format=self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size=size, scale_factor=scale_factor, mode="bilinear",
                         align_corners=True, data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size=size, scale_factor=scale_factor, mode="nearest",
                         data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW"):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


Pad1D = Pad2D
Pad3D = Pad2D


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                   keepdim=self.keepdim)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class ZeroPad2D(Layer):
    """Reference nn/layer/common.py ZeroPad2D."""

    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._padding = padding
        self._data_format = data_format

    def forward(self, x):
        from ... import ops
        p = self._padding
        if isinstance(p, int):
            p = [p, p, p, p]
        return ops.pad(x, [0, 0, 0, 0, p[2], p[3], p[0], p[1]]
                       if self._data_format == "NCHW" else
                       [0, 0, p[2], p[3], p[0], p[1], 0, 0])


class Bilinear(Layer):
    """Reference nn/layer/common.py Bilinear: x1 W x2 + b."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x1, x2):
        from .. import functional as F
        return F.bilinear(x1, x2, self.weight, self.bias)


class Fold(Layer):
    """Reference nn/layer/common.py Fold (col2im)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        from .. import functional as F
        return F.fold(x, *self._args)
