"""paddle.hub (reference: python/paddle/hub.py): load models from a local
repo dir (github/gitee sources need egress, so only the 'local' source is
live; remote sources raise with a clear message)."""
import os
import sys
import importlib

__all__ = ["list", "help", "load"]

_HUB_CONF = "hubconf.py"


def _load_entry_file(repo_dir):
    conf = os.path.join(repo_dir, _HUB_CONF)
    if not os.path.exists(conf):
        raise FileNotFoundError(f"no {_HUB_CONF} in {repo_dir}")
    sys.path.insert(0, repo_dir)
    try:
        spec = importlib.util.spec_from_file_location("hubconf", conf)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    finally:
        sys.path.remove(repo_dir)


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"hub source '{source}' needs network egress; this environment "
            "supports source='local' (a directory containing hubconf.py)")


def list(repo_dir, source="local", force_reload=False):
    """List callable entrypoints exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_entry_file(repo_dir)
    return [n for n, f in vars(mod).items()
            if callable(f) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    """Docstring of one entrypoint."""
    _check_source(source)
    mod = _load_entry_file(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model '{model}' not found in {repo_dir}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate an entrypoint."""
    _check_source(source)
    mod = _load_entry_file(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model '{model}' not found in {repo_dir}")
    return fn(**kwargs)


def load_state_dict_from_url(url, model_dir=None, check_hash=False,
                             file_name=None, map_location=None):
    """Reference downloads a checkpoint; zero-egress here — loads from a
    local path or a file already in model_dir."""
    import os
    from .framework import load as _load
    if os.path.exists(url):
        return _load(url)
    cand = os.path.join(model_dir or ".", file_name or os.path.basename(url))
    if os.path.exists(cand):
        return _load(cand)
    raise RuntimeError(
        "load_state_dict_from_url needs network egress; place the file at "
        f"'{cand}' and pass that path instead")


__all__.append("load_state_dict_from_url")
