"""paddle.fft parity namespace (reference: python/paddle/fft.py — ~30
functions over the phi fft kernels, which bind cuFFT/onednn; here they
lower to jnp.fft = XLA's native FFT ops, differentiable through the
dispatch tape)."""
import jax.numpy as jnp

from .core.dispatch import apply_op

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq",
]

_NORMS = {"backward", "ortho", "forward", None}


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return norm


def _wrap1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward"):
        _check_norm(norm)

        def impl(a):
            return jfn(a, n=n, axis=axis, norm=norm)

        return apply_op(f"fft_{name}", impl, (x,), {})
    op.__name__ = name
    op.__doc__ = f"paddle.fft.{name} (jnp.fft.{jfn.__name__} lowering)."
    return op


def _wrap2(name, jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward"):
        _check_norm(norm)

        def impl(a):
            return jfn(a, s=s, axes=axes, norm=norm)

        return apply_op(f"fft_{name}", impl, (x,), {})
    op.__name__ = name
    return op


def _wrapn(name, jfn):
    def op(x, s=None, axes=None, norm="backward"):
        _check_norm(norm)

        def impl(a):
            return jfn(a, s=s, axes=axes, norm=norm)

        return apply_op(f"fft_{name}", impl, (x,), {})
    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)

fft2 = _wrap2("fft2", jnp.fft.fft2)
ifft2 = _wrap2("ifft2", jnp.fft.ifft2)
rfft2 = _wrap2("rfft2", jnp.fft.rfft2)
irfft2 = _wrap2("irfft2", jnp.fft.irfft2)


fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def _hfftn_impl(a, s, axes, norm):
    """hfftn = fftn over the leading axes composed with hfft on the last
    (norms are per-axis multiplicative, so composition preserves all three
    norm modes). jnp.fft has only the 1D hfft/ihfft."""
    axes = tuple(range(-a.ndim, 0)) if axes is None else tuple(axes)
    n_last = None if s is None else s[-1]
    out = a
    if len(axes) > 1:
        s_head = None if s is None else s[:-1]
        out = jnp.fft.fftn(out, s=s_head, axes=axes[:-1], norm=norm)
    return jnp.fft.hfft(out, n=n_last, axis=axes[-1], norm=norm)


def _ihfftn_impl(a, s, axes, norm):
    axes = tuple(range(-a.ndim, 0)) if axes is None else tuple(axes)
    n_last = None if s is None else s[-1]
    out = jnp.fft.ihfft(a, n=n_last, axis=axes[-1], norm=norm)
    if len(axes) > 1:
        s_head = None if s is None else s[:-1]
        out = jnp.fft.ifftn(out, s=s_head, axes=axes[:-1], norm=norm)
    return out


def hfftn(x, s=None, axes=None, norm="backward"):
    _check_norm(norm)
    return apply_op("fft_hfftn",
                    lambda a: _hfftn_impl(a, s, axes, norm), (x,), {})


def ihfftn(x, s=None, axes=None, norm="backward"):
    _check_norm(norm)
    return apply_op("fft_ihfftn",
                    lambda a: _ihfftn_impl(a, s, axes, norm), (x,), {})


def hfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def fftshift(x, axes=None):
    def impl(a):
        return jnp.fft.fftshift(a, axes=axes)
    return apply_op("fftshift", impl, (x,), {})


def ifftshift(x, axes=None):
    def impl(a):
        return jnp.fft.ifftshift(a, axes=axes)
    return apply_op("ifftshift", impl, (x,), {})


def fftfreq(n, d=1.0, dtype=None):
    from .core.tensor import to_tensor
    import numpy as np
    return to_tensor(np.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None):
    from .core.tensor import to_tensor
    import numpy as np
    return to_tensor(np.fft.rfftfreq(n, d).astype(dtype or "float32"))
