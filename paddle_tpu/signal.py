"""paddle.signal parity (reference: python/paddle/signal.py — stft/istft
over the frame/overlap_add phi kernels). Framing is a strided gather;
overlap-add is a scatter-add — both XLA-native."""
import jax.numpy as jnp
import numpy as np

from .core.dispatch import apply_op
from .core.tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1):
    """Slice overlapping frames (reference signal.py frame, kernel
    funcs/frame_functor.h). axis=-1: [..., T] -> [..., frame_length, n];
    axis=0: [T, ...] -> [n, frame_length, ...]."""
    if axis not in (0, -1):
        raise ValueError("frame supports axis 0 or -1")

    def impl(a):
        if axis == 0:
            a = jnp.moveaxis(a, 0, -1)
        t = a.shape[-1]
        n = 1 + (t - frame_length) // hop_length
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = a[..., idx]            # [..., n, frame_length]
        if axis == 0:
            # [..., n, fl] -> [n, fl, ...]
            return jnp.moveaxis(jnp.moveaxis(out, -1, 0), -1, 0)
        return jnp.moveaxis(out, -2, -1)
    return apply_op("frame", impl, (x,), {})


def overlap_add(x, hop_length, axis=-1):
    """Inverse of frame. axis=-1: [..., frame_length, n] -> [..., T];
    axis=0: [n, frame_length, ...] -> [T, ...]."""
    if axis not in (0, -1):
        raise ValueError("overlap_add supports axis 0 or -1")

    def impl(a):
        if axis == 0:
            # [n, fl, ...] -> [..., fl, n]
            a = jnp.moveaxis(jnp.moveaxis(a, 0, -1), 0, -2)
        fl, n = a.shape[-2], a.shape[-1]
        t = (n - 1) * hop_length + fl
        starts = jnp.arange(n) * hop_length
        idx = (starts[None, :] + jnp.arange(fl)[:, None]).reshape(-1)
        flat = a.reshape(a.shape[:-2] + (fl * n,))
        out = jnp.zeros(a.shape[:-2] + (t,), a.dtype)
        out = out.at[..., idx].add(flat)
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out
    return apply_op("overlap_add", impl, (x,), {})


def _window_array(window, n_fft):
    if window is None:
        return jnp.ones((n_fft,), jnp.float32)
    if isinstance(window, Tensor):
        return window.data
    return jnp.asarray(np.asarray(window), jnp.float32)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True):
    """Short-time Fourier transform (reference signal.py:141). Input
    [B, T] or [T]; output [B, n_fft//2+1, n_frames] complex (onesided)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if win_length > n_fft:
        raise ValueError(f"win_length ({win_length}) must be <= n_fft "
                         f"({n_fft})")
    w = _window_array(window, win_length)
    if win_length < n_fft:  # center-pad window to n_fft
        pad = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad, n_fft - win_length - pad))

    def impl(a):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            a = jnp.pad(a, ((0, 0), (n_fft // 2, n_fft // 2)),
                        mode=pad_mode)
        t = a.shape[-1]
        n = 1 + (t - n_fft) // hop_length
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[:, idx] * w          # [B, n, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -2, -1)  # [B, freq, n]
        return out[0] if squeeze else out

    return apply_op("stft", impl, (x,), {})


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False):
    """Inverse STFT with window-envelope normalization (reference
    signal.py:334)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if win_length > n_fft:
        raise ValueError(f"win_length ({win_length}) must be <= n_fft "
                         f"({n_fft})")
    w = _window_array(window, win_length)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad, n_fft - win_length - pad))

    if return_complex and onesided:
        raise ValueError("return_complex=True requires onesided=False "
                         "(a onesided spectrum reconstructs a real signal)")

    def impl(spec):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        spec = jnp.swapaxes(spec, -2, -1)      # [B, n, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * w                     # [B, n, n_fft]
        n = frames.shape[1]
        t = (n - 1) * hop_length + n_fft
        starts = jnp.arange(n) * hop_length
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        sig = jnp.zeros((frames.shape[0], t), frames.dtype)
        sig = sig.at[:, idx].add(frames.reshape(frames.shape[0], -1))
        env = jnp.zeros((t,), frames.dtype).at[idx].add(
            jnp.tile(w * w, n))
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            sig = sig[:, n_fft // 2: t - n_fft // 2]
        if length is not None:
            sig = sig[:, :length]
        return sig[0] if squeeze else sig

    return apply_op("istft", impl, (x,), {})
