"""Functional quasi-Newton minimizers (reference:
python/paddle/incubate/optimizer/functional/{bfgs,lbfgs}.py —
minimize_bfgs / minimize_lbfgs returning
(is_converge, num_func_calls, position, objective_value,
objective_gradient)).

TPU-native: the whole minimization is ONE `lax.while_loop` program — the
objective's value-and-grad, the line search, and the (inverse-Hessian |
two-loop-recursion) update all trace into a single XLA computation, instead
of the reference's per-iteration op dispatch. Static shapes throughout:
L-BFGS history lives in fixed `(history_size, n)` buffers with a rolling
index, so the compiled program is iteration-count independent.
"""
from functools import partial

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _pure_objective(objective_func):
    def f(x):
        out = objective_func(Tensor(x))
        out = out.data if isinstance(out, Tensor) else jnp.asarray(out)
        return out.reshape(())
    return f


def _line_search(f, xk, fk, gk, pk, max_ls, alpha0):
    """Backtracking line search with the Armijo sufficient-decrease rule
    (the decrease half of strong-Wolfe; curvature is enforced by the
    rho>0 guard in the update). Returns (alpha, f_new, g_new, n_evals)."""
    c1 = 1e-4
    gtp = jnp.vdot(gk, pk)

    def cond(state):
        alpha_try, alpha_eval, fv, _, it, done = state
        return jnp.logical_and(it < max_ls, jnp.logical_not(done))

    def body(state):
        alpha_try, _, _, _, it, _ = state
        fv, gv = jax.value_and_grad(f)(xk + alpha_try * pk)
        ok = fv <= fk + c1 * alpha_try * gtp
        # alpha_eval tracks the step f/g were ACTUALLY evaluated at, so an
        # exhausted search still returns a consistent (alpha, f, g) triple
        next_alpha = jnp.where(ok, alpha_try, alpha_try * 0.5)
        return (next_alpha, alpha_try, fv, gv, it + 1, ok)

    f0, g0 = jax.value_and_grad(f)(xk + alpha0 * pk)
    ok0 = f0 <= fk + c1 * alpha0 * gtp
    _, alpha, fv, gv, evals, done = jax.lax.while_loop(
        cond, body, (jnp.where(ok0, alpha0, alpha0 * 0.5), alpha0, f0, g0,
                     jnp.asarray(1), ok0))
    return alpha, fv, gv, evals, done


def _prep(initial_position, dtype):
    x0 = initial_position.data if isinstance(initial_position, Tensor) \
        else jnp.asarray(initial_position)
    return x0.astype(dtype).reshape(-1), x0.shape


@partial(jax.jit, static_argnums=(0, 2, 6))
def _bfgs_impl(f, x0, max_iters, tol_grad, tol_change, h0, max_ls, alpha0):
    n = x0.shape[0]
    f0, g0 = jax.value_and_grad(f)(x0)

    def cond(s):
        k, x, fv, g, H, calls, conv = s
        return jnp.logical_and(k < max_iters, jnp.logical_not(conv))

    def body(s):
        k, x, fv, g, H, calls, _ = s
        p = -(H @ g)
        alpha, f1, g1, evals, ls_ok = _line_search(
            f, x, fv, g, p, max_ls, alpha0)
        sk = alpha * p
        x1 = x + sk
        yk = g1 - g
        sy = jnp.vdot(sk, yk)
        rho = jnp.where(sy > 1e-10, 1.0 / jnp.where(sy > 1e-10, sy, 1.0), 0.0)
        eye = jnp.eye(n, dtype=x.dtype)
        # standard first-iteration scaling H <- (s.y / y.y) I before the
        # update: makes the initial inverse-Hessian magnitude match the
        # local curvature so unit steps are accepted
        yy = jnp.vdot(yk, yk)
        Hs = jnp.where(jnp.logical_and(k == 0, sy > 1e-10),
                       (sy / jnp.where(yy > 0, yy, 1.0)) * eye, H)
        V = eye - rho * jnp.outer(sk, yk)
        H1 = jnp.where(rho > 0,
                       V @ Hs @ V.T + rho * jnp.outer(sk, sk), H)
        conv = jnp.logical_or(
            jnp.max(jnp.abs(g1)) < tol_grad,
            jnp.max(jnp.abs(sk)) < tol_change)
        return (k + 1, x1, f1, g1, H1, calls + evals, conv)

    k, x, fv, g, H, calls, conv = jax.lax.while_loop(
        cond, body, (jnp.asarray(0), x0, f0, g0, h0, jnp.asarray(1),
                     jnp.max(jnp.abs(g0)) < tol_grad))
    return conv, calls, x, fv, g


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    """Minimize `objective_func` from `initial_position` with BFGS.
    Returns (is_converge, num_func_calls, position, objective_value,
    objective_gradient) — the reference bfgs.py contract."""
    x0, shape = _prep(initial_position, dtype)
    f = _pure_objective(
        lambda t: objective_func(Tensor(t.data.reshape(shape))))
    n = x0.shape[0]
    if initial_inverse_hessian_estimate is not None:
        h0 = initial_inverse_hessian_estimate
        h0 = (h0.data if isinstance(h0, Tensor) else jnp.asarray(h0))
        h0 = h0.astype(x0.dtype)
    else:
        h0 = jnp.eye(n, dtype=x0.dtype)
    conv, calls, x, fv, g = _bfgs_impl(
        f, x0, int(max_iters), float(tolerance_grad),
        float(tolerance_change), h0, int(max_line_search_iters),
        float(initial_step_length))
    return (Tensor(conv), Tensor(calls), Tensor(x.reshape(shape)),
            Tensor(fv), Tensor(g.reshape(shape)))


@partial(jax.jit, static_argnums=(0, 2, 5, 6))
def _lbfgs_impl(f, x0, max_iters, tol_grad, tol_change, m, max_ls, alpha0):
    n = x0.shape[0]
    f0, g0 = jax.value_and_grad(f)(x0)
    S = jnp.zeros((m, n), dtype=x0.dtype)
    Y = jnp.zeros((m, n), dtype=x0.dtype)
    R = jnp.zeros((m,), dtype=x0.dtype)  # rho_i; 0 marks an empty slot

    def direction(g, S, Y, R, gamma, k):
        """Two-loop recursion over the rolling history in age order
        (newest first on the backward pass, oldest first forward); empty
        slots have rho==0 so their contribution vanishes."""
        def bwd(j, carry):
            q, a = carry
            i = jnp.mod(k - 1 - j, m)  # newest -> oldest
            ai = R[i] * jnp.vdot(S[i], q)
            return (q - ai * Y[i], a.at[i].set(ai))

        q, a = jax.lax.fori_loop(
            0, m, bwd, (g, jnp.zeros((m,), dtype=g.dtype)))
        r = gamma * q

        def fwd(j, r):
            i = jnp.mod(k - m + j, m)  # oldest -> newest
            bi = R[i] * jnp.vdot(Y[i], r)
            return r + S[i] * (a[i] - bi)

        return -jax.lax.fori_loop(0, m, fwd, r)

    def cond(s):
        k, x, fv, g, S, Y, R, gamma, calls, conv = s
        return jnp.logical_and(k < max_iters, jnp.logical_not(conv))

    def body(s):
        k, x, fv, g, S, Y, R, gamma, calls, _ = s
        p = direction(g, S, Y, R, gamma, k)
        alpha, f1, g1, evals, ls_ok = _line_search(
            f, x, fv, g, p, max_ls, alpha0)
        sk = alpha * p
        x1 = x + sk
        yk = g1 - g
        sy = jnp.vdot(sk, yk)
        good = sy > 1e-10
        slot = k % m  # rolling history window
        S1 = jnp.where(good, S.at[slot].set(sk), S)
        Y1 = jnp.where(good, Y.at[slot].set(yk), Y)
        R1 = jnp.where(good,
                       R.at[slot].set(1.0 / jnp.where(good, sy, 1.0)), R)
        gamma1 = jnp.where(good, sy / jnp.vdot(yk, yk), gamma)
        conv = jnp.logical_or(
            jnp.max(jnp.abs(g1)) < tol_grad,
            jnp.max(jnp.abs(sk)) < tol_change)
        return (k + 1, x1, f1, g1, S1, Y1, R1, gamma1,
                calls + evals, conv)

    s0 = (jnp.asarray(0), x0, f0, g0, S, Y, R,
          jnp.asarray(1.0, dtype=x0.dtype), jnp.asarray(1),
          jnp.max(jnp.abs(g0)) < tol_grad)
    k, x, fv, g, S, Y, R, gamma, calls, conv = jax.lax.while_loop(
        cond, body, s0)
    return conv, calls, x, fv, g


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7, tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    """Limited-memory BFGS with a fixed `(history_size, n)` rolling window
    (reference lbfgs.py contract; same return tuple as minimize_bfgs)."""
    x0, shape = _prep(initial_position, dtype)
    f = _pure_objective(
        lambda t: objective_func(Tensor(t.data.reshape(shape))))
    conv, calls, x, fv, g = _lbfgs_impl(
        f, x0, int(max_iters), float(tolerance_grad),
        float(tolerance_change), int(min(history_size, max(1, max_iters))),
        int(max_line_search_iters), float(initial_step_length))
    return (Tensor(conv), Tensor(calls), Tensor(x.reshape(shape)),
            Tensor(fv), Tensor(g.reshape(shape)))
