"""Incubating optimizers (reference: python/paddle/incubate/optimizer/ —
LookAhead, ModelAverage, LBFGS, and the functional bfgs/lbfgs minimizers)."""
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer
from ...optimizer.optimizers import LBFGS  # noqa: F401
from . import functional  # noqa: F401

__all__ = ["LookAhead", "ModelAverage", "LBFGS", "functional"]


class LookAhead(Optimizer):
    """Lookahead wrapper (reference incubate/optimizer/lookahead.py):
    k fast steps with the inner optimizer, then a slow interpolation
    toward the fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        super().__init__(inner_optimizer.get_lr(),
                         inner_optimizer._parameter_list, None, None,
                         False, name)
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._steps = 0

    def step(self):
        self.inner.step()
        self._steps += 1
        if self._steps % self.k:
            return
        for p in self._parameter_list:
            slow = self._slow.get(id(p))
            if slow is None:
                slow = self._slow[id(p)] = p.data.astype(jnp.float32)
                continue
            slow = slow + self.alpha * (p.data.astype(jnp.float32) - slow)
            self._slow[id(p)] = slow
            p.data = slow.astype(p.data.dtype)

    def clear_grad(self, set_to_zero=False):
        self.inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad


class ModelAverage(Optimizer):
    """Running parameter average for evaluation (reference
    incubate/optimizer/modelaverage.py): apply()/restore() swap averaged
    weights in and out."""

    def __init__(self, inner_optimizer_or_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if isinstance(inner_optimizer_or_rate, Optimizer):
            inner = inner_optimizer_or_rate
            params = inner._parameter_list
            self.inner = inner
        else:
            self.inner = None
            params = parameters
        super().__init__(0.0, params, None, None, False, name)
        self._min_w = min_average_window
        self._max_w = max_average_window
        # reference windowing: accumulate into `sum`; when the window
        # exceeds max_average_window, roll it into (old_sum, old_num) and
        # restart — apply() averages over sum+old_sum (>= min window)
        self._sum = {id(p): jnp.zeros(p.data.shape, jnp.float32)
                     for p in self._parameter_list}
        self._old_sum = {id(p): jnp.zeros(p.data.shape, jnp.float32)
                         for p in self._parameter_list}
        self._count = 0
        self._old_count = 0
        self._backup = None

    def step(self):
        if self.inner is not None:
            self.inner.step()
        for p in self._parameter_list:
            self._sum[id(p)] = self._sum[id(p)] + p.data.astype(jnp.float32)
        self._count += 1
        if self._count >= self._max_w and self._count >= self._min_w:
            self._old_sum = dict(self._sum)
            self._old_count = self._count
            self._sum = {k: jnp.zeros_like(v) for k, v in self._sum.items()}
            self._count = 0

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p.data for p in self._parameter_list}
        total = self._count + self._old_count
        if not total:
            return
        for p in self._parameter_list:
            avg = (self._sum[id(p)] + self._old_sum[id(p)]) / total
            p.data = avg.astype(p.data.dtype)

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameter_list:
                p.data = self._backup[id(p)]
            self._backup = None

    def clear_grad(self, set_to_zero=False):
        if self.inner is not None:
            self.inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad