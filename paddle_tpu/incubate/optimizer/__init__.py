"""Incubating optimizers (reference: python/paddle/incubate/optimizer/ —
LookAhead, ModelAverage, LBFGS, and the functional bfgs/lbfgs minimizers)."""
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer
from ...optimizer.optimizers import LBFGS  # noqa: F401
from ...optimizer.optimizers import Lamb as _Lamb
from . import functional  # noqa: F401

__all__ = ["LookAhead", "ModelAverage", "LBFGS", "DistributedFusedLamb",
           "functional"]


class LookAhead(Optimizer):
    """Lookahead wrapper (reference incubate/optimizer/lookahead.py):
    k fast steps with the inner optimizer, then a slow interpolation
    toward the fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        super().__init__(inner_optimizer.get_lr(),
                         inner_optimizer._parameter_list, None, None,
                         False, name)
        self.alpha = alpha
        self.k = k
        # Slow weights snapshot the params AT CONSTRUCTION (reference
        # lookahead.py), so the first k-boundary performs a real
        # interpolation rather than a no-op re-snapshot.
        self._slow = {id(p): p.data.astype(jnp.float32)
                      for p in self._parameter_list}
        self._steps = 0

    def step(self):
        self.inner.step()
        self._steps += 1
        if self._steps % self.k:
            return
        for p in self._parameter_list:
            slow = self._slow.get(id(p))
            if slow is None:  # param added after construction
                self._slow[id(p)] = p.data.astype(jnp.float32)
                continue
            slow = slow + self.alpha * (p.data.astype(jnp.float32) - slow)
            self._slow[id(p)] = slow
            p.data = slow.astype(p.data.dtype)

    def clear_grad(self, set_to_zero=False):
        self.inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad


class ModelAverage(Optimizer):
    """Running parameter average for evaluation (reference
    incubate/optimizer/modelaverage.py): apply()/restore() swap averaged
    weights in and out."""

    def __init__(self, inner_optimizer_or_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if isinstance(inner_optimizer_or_rate, Optimizer):
            inner = inner_optimizer_or_rate
            params = inner._parameter_list
            self.inner = inner
        else:
            self.inner = None
            params = parameters
        super().__init__(0.0, params, None, None, False, name)
        self._min_w = min_average_window
        self._max_w = max_average_window
        # reference windowing: accumulate into `sum`; when the window
        # exceeds max_average_window, roll it into (old_sum, old_num) and
        # restart — apply() averages over sum+old_sum (>= min window)
        self._sum = {id(p): jnp.zeros(p.data.shape, jnp.float32)
                     for p in self._parameter_list}
        self._old_sum = {id(p): jnp.zeros(p.data.shape, jnp.float32)
                         for p in self._parameter_list}
        self._count = 0
        self._old_count = 0
        self._backup = None

    def step(self):
        if self.inner is not None:
            self.inner.step()
        for p in self._parameter_list:
            self._sum[id(p)] = self._sum[id(p)] + p.data.astype(jnp.float32)
        self._count += 1
        if self._count >= self._max_w and self._count >= self._min_w:
            self._old_sum = dict(self._sum)
            self._old_count = self._count
            self._sum = {k: jnp.zeros_like(v) for k, v in self._sum.items()}
            self._count = 0

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p.data for p in self._parameter_list}
        total = self._count + self._old_count
        if not total:
            return
        for p in self._parameter_list:
            avg = (self._sum[id(p)] + self._old_sum[id(p)]) / total
            p.data = avg.astype(p.data.dtype)

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameter_list:
                p.data = self._backup[id(p)]
            self._backup = None

    def clear_grad(self, set_to_zero=False):
        if self.inner is not None:
            self.inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad


class DistributedFusedLamb(_Lamb):
    """Distributed LAMB (reference
    python/paddle/incubate/optimizer/distributed_fused_lamb.py:120 over the
    distributed_fused_lamb CUDA kernels, SURVEY §2.9): LAMB whose gradient
    sync, clipping, and trust-ratio math run as one fused step across the
    data-parallel group.

    TPU mapping: the CUDA kernel's flat-buffer fusion is XLA's job — each
    step here is jitted LAMB math; the distributed part is the dp-group
    all-reduce (+1/n scaling per is_grad_scaled_by_nranks) executed before
    or after clipping per `clip_after_allreduce`, and
    `gradient_accumulation_steps` micro-batch accumulation. Sharded
    optimizer states belong to the traced pretrain path
    (models/pretrain.py shards moments over the fsdp axis)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, use_hierarchical_allreduce=False,
                 name=None):
        super().__init__(
            learning_rate=learning_rate, lamb_weight_decay=lamb_weight_decay,
            beta1=beta1, beta2=beta2, epsilon=epsilon, parameters=parameters,
            grad_clip=grad_clip if clip_after_allreduce else None,
            exclude_from_weight_decay_fn=exclude_from_weight_decay_fn,
            multi_precision=use_master_param_norm)
        self._pre_clip = None if clip_after_allreduce else grad_clip
        self._scaled_by_nranks = is_grad_scaled_by_nranks
        self._acc_steps = int(gradient_accumulation_steps)
        self._acc_count = 0
        self._acc = {}

    def _dp_group(self):
        from ...distributed.fleet import get_hcg
        hcg = get_hcg()
        if hcg is None:
            return None
        g = hcg.get_data_parallel_group()
        return g if getattr(g, "nranks", 1) > 1 else None

    def step(self):
        from ...core.tensor import Tensor as _T

        params = [p for p in self._parameter_list
                  if getattr(p, "grad", None) is not None]
        # micro-batch accumulation (reference gradient_accumulation_steps)
        if self._acc_steps > 1:
            self._acc_count += 1
            for p in params:
                a = self._acc.get(id(p))
                g32 = p.grad.data.astype(jnp.float32)
                self._acc[id(p)] = g32 if a is None else a + g32
                p.grad = None
            if self._acc_count < self._acc_steps:
                return
            for p in params:
                p.grad = _T((self._acc.pop(id(p), 0.0)
                             / self._acc_steps).astype(p.data.dtype))
            self._acc_count = 0
        if self._pre_clip is not None:
            # clip objects take and return (param, grad) pairs (the
            # Optimizer.step contract); write the clipped grads back
            pairs = self._pre_clip([(p, p.grad) for p in params])
            for p, g in pairs:
                p.grad = g
        group = self._dp_group()
        if group is not None:
            from ...distributed import collective as _c
            n = group.nranks
            for p in params:
                _c.all_reduce(p.grad, group=group)
                if self._scaled_by_nranks:
                    p.grad = _T(p.grad.data / n)
        return super().step()
