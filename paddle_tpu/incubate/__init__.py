"""Staging namespace (reference: python/paddle/incubate/ — fused-op python
bindings, MoE, asp sparsity, autograd extras)."""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
