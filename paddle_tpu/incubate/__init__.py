"""Staging namespace (reference: python/paddle/incubate/ — fused-op python
bindings, MoE, asp sparsity, autograd extras)."""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

# reference-parity aliases: segment/graph ops + fused softmax-mask live at
# paddle.incubate.* too (python/paddle/incubate/__init__.py)
from ..geometric import (  # noqa: F401
    segment_sum, segment_mean, segment_max, segment_min,
    send_u_recv as graph_send_recv, reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, **kw):
    """Multi-hop sampling by chaining sample_neighbors (reference
    graph_khop_sampler): returns (edge_src, edge_dst, sample_index,
    reindex_nodes[, edge_eids]) — the reindexed sampled subgraph.
    sample_index holds the original node ids in new-id order."""
    import numpy as np
    from ..geometric import sample_neighbors
    from ..core.tensor import Tensor
    from .. import ops
    nodes = input_nodes
    srcs, dsts, eids = [], [], []
    for k in sample_sizes:
        res = sample_neighbors(row, colptr, nodes, sample_size=k,
                               eids=sorted_eids,
                               return_eids=sorted_eids is not None)
        if sorted_eids is not None:
            out, counts, eid = res
            eids.append(eid)
        else:
            out, counts = res
        # each sampled neighbor's dst is the node it was drawn for,
        # repeated per-count
        n_np = np.asarray(nodes.numpy() if isinstance(nodes, Tensor)
                          else nodes).reshape(-1)
        c_np = np.asarray(counts.numpy() if isinstance(counts, Tensor)
                          else counts).reshape(-1)
        dsts.append(Tensor(np.repeat(n_np, c_np)))
        srcs.append(out)
        nodes = out
    edge_src = ops.concat(srcs)
    edge_dst = ops.concat(dsts)
    seeds = input_nodes if isinstance(input_nodes, Tensor) \
        else Tensor(np.asarray(input_nodes))
    (edge_src_r, edge_dst_r, sample_index), _ = _khop_reindex(
        seeds, edge_src, edge_dst)
    # reindex_nodes: the new (compacted) ids of the seed nodes
    reindex_nodes = Tensor(np.arange(len(np.asarray(seeds.numpy()).reshape(-1)),
                                     dtype=np.int64))
    out = (edge_src_r, edge_dst_r, sample_index, reindex_nodes)
    if return_eids:
        if not eids:
            raise ValueError("return_eids=True requires sorted_eids")
        out = out + (ops.concat(eids),)
    return out


def _khop_reindex(seeds, edge_src, edge_dst):
    import numpy as np
    from ..core.tensor import Tensor
    s = np.asarray(seeds.numpy()).reshape(-1)
    es = np.asarray(edge_src.numpy()).reshape(-1)
    ed = np.asarray(edge_dst.numpy()).reshape(-1)
    order = list(dict.fromkeys(np.concatenate([s, es, ed]).tolist()))
    remap = {v: i for i, v in enumerate(order)}
    esr = np.asarray([remap[v] for v in es.tolist()], np.int64)
    edr = np.asarray([remap[v] for v in ed.tolist()], np.int64)
    sample_index = Tensor(np.asarray(order, s.dtype))
    return (Tensor(esr), Tensor(edr), sample_index), None


def identity_loss(x, reduction="none"):
    """Mark a value as a loss for IPU-style pipelines; on this stack it is
    reduction only (reference incubate.identity_loss)."""
    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 1):
        return x.sum()
    return x.mean()


def softmax_mask_fuse(x, mask):
    """softmax(x + mask) fused by XLA (reference fused_softmax_mask op)."""
    from ..nn import functional as F
    return F.softmax(x + mask, axis=-1)


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (reference fused_softmax_mask_upper_triangle):
    masks strictly-upper triangle before softmax."""
    import jax.numpy as jnp
    from ..core.dispatch import apply_op
    import jax

    def impl(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, a, jnp.finfo(jnp.float32).min)
        return jax.nn.softmax(logits.astype(jnp.float32), -1).astype(a.dtype)
    return apply_op("softmax_mask_fuse_upper_triangle", impl, (x,), {})


from . import inference  # noqa: F401
