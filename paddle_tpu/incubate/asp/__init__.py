"""2:4 structured sparsity (reference: python/paddle/incubate/asp/).
Populated by the asp milestone."""
