"""2:4 structured sparsity — ASP (reference: python/paddle/incubate/asp/
— calculate_density, create_mask m4n2 patterns, prune_model, decorate).

TPU note: the MXU has no 2:4 sparse mode (that is an Ampere tensor-core
feature), so ASP here is the *training-method* parity: masks are computed
the same way and enforced through the optimizer step, giving models that
deploy efficiently on hardware that does have structured sparsity."""
import numpy as np

from ...core.tensor import Tensor, to_tensor

__all__ = ["calculate_density", "create_mask", "check_mask_1d",
           "check_mask_2d", "prune_model", "decorate", "reset_excluded_layers",
           "set_excluded_layers", "add_supported_layer"]

import weakref

_excluded = set()
_pruned_models = []  # weakrefs of every prune_model target
_supported_layer_types = set()  # extra layer classes opted into pruning
_custom_pruning = {}  # layer-type name -> pruning func


def add_supported_layer(layer, pruning_func=None):
    """Opt a layer type into ASP pruning (reference
    incubate/asp/supported_layer_list.py add_supported_layer): `layer` is a
    Layer subclass or its type name; `pruning_func(weight_np, m, n,
    mask_algo, param_name) -> (pruned_np, mask_np)` overrides the default
    n:m masking for that type's parameters."""
    name = layer if isinstance(layer, str) else getattr(
        layer, "__name__", str(layer))
    _supported_layer_types.add(name)
    if pruning_func is not None:
        _custom_pruning[name] = pruning_func


def calculate_density(x):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """Best-n-of-m mask along the last axis (reference create_mask
    mask_1d/mask_2d_best). Keeps the n largest |values| in every group of
    m."""
    arr = np.asarray(tensor.numpy() if isinstance(tensor, Tensor)
                     else tensor)
    flat = arr.reshape(-1, arr.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    padded = np.pad(np.abs(flat), ((0, 0), (0, pad)))
    groups = padded.reshape(flat.shape[0], -1, m)
    order = np.argsort(-groups, axis=-1)
    mask_g = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask_g, order[..., :n], True, axis=-1)
    mask = mask_g.reshape(flat.shape[0], -1)[:, :cols].reshape(arr.shape)
    return to_tensor(mask.astype(arr.dtype))


def check_mask_1d(mat, n=2, m=4):
    arr = np.asarray(mat.numpy() if isinstance(mat, Tensor) else mat)
    flat = arr.reshape(-1)
    pad = (-flat.size) % m
    groups = np.pad(flat != 0, (0, pad)).reshape(-1, m)
    return bool((groups.sum(axis=1) <= n).all())


def check_mask_2d(mat, n=2, m=4):
    return check_mask_1d(mat, n, m)


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _prunable(name, p):
    return p.data.ndim >= 2 and name not in _excluded \
        and not any(name.endswith(sfx) for sfx in ("bias",))


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every prunable parameter; returns name->mask
    (reference prune_model). Masks are also stashed on the model for the
    decorated optimizer to re-apply after each step."""
    # map param name -> owning sublayer type, for custom pruning funcs
    # registered via add_supported_layer
    owner_type = {}
    for lname, sub in model.named_sublayers():
        for pname, _ in sub.named_parameters(include_sublayers=False):
            owner_type[f"{lname}.{pname}" if lname else pname] = \
                type(sub).__name__
    masks = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        custom = _custom_pruning.get(owner_type.get(name))
        if custom is not None:
            pruned, mask_np = custom(np.asarray(p.numpy()), m, n,
                                     mask_algo, name)
            p.set_value(np.asarray(pruned))
            masks[name] = to_tensor(np.asarray(mask_np))
            continue
        mask = create_mask(p, mask_algo, n, m)
        p.set_value(np.asarray(p.numpy()) * np.asarray(mask.numpy()))
        masks[name] = mask
    model._asp_masks = masks
    _pruned_models.append(weakref.ref(model))
    return masks


def decorate(optimizer, model=None):
    """Wrap optimizer.step to re-apply the sparsity masks after every
    update (reference ASPOptimizer/OptimizerWithSparsityGuarantee).
    Without an explicit `model`, every model previously passed to
    prune_model is re-masked — decorate(optimizer) alone must guarantee
    sparsity, as the reference's does."""
    orig_step = optimizer.step

    def step():
        orig_step()
        if model is not None:
            models = [model]
        else:
            models = [m for m in (r() for r in _pruned_models)
                      if m is not None]
        for mdl in models:
            masks = getattr(mdl, "_asp_masks", None)
            if not masks:
                continue
            for name, p in mdl.named_parameters():
                msk = masks.get(name)
                if msk is not None:
                    p.data = p.data * msk.data

    optimizer.step = step
    return optimizer
