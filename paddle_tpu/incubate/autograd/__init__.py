"""Higher-order autodiff extras (reference: python/paddle/incubate/
autograd/__init__.py — vjp/jvp, the lazy Jacobian/Hessian views, the
functional forward_grad/grad, and the prim-decomposition switches).

TPU-native: jacobian/hessian lower to jax.jacrev/jax.hessian; forward_grad
is forward-mode (jax.jvp over the functionalized relation);
enable_prim/disable_prim only record a preference — under XLA every op is
ALWAYS decomposed to primitives at trace time (the reference needs the
switch because its eager kernels are monolithic; here 'prim' is
structurally always on)."""
import jax
import jax.numpy as jnp

from ...autograd.functional import (  # noqa: F401
    jacobian, hessian, vjp, jvp, _functionalize,
)
from ...core.autograd import grad as _tape_grad
from ...core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "jacobian", "hessian",
           "enable_prim", "disable_prim", "prim_enabled", "forward_grad",
           "grad"]

_prim_enabled = [False]


def enable_prim():
    """Record the prim preference (reference: switch grads to composite
    primitive rules so the compiler sees only primitives). Decomposition is
    structural here — every op traces to XLA primitives unconditionally —
    so the flag exists for source compatibility and introspection."""
    _prim_enabled[0] = True


def disable_prim():
    _prim_enabled[0] = False


def prim_enabled():
    return _prim_enabled[0]


class Jacobian:
    """Lazy Jacobian view (reference incubate/autograd/functional.py
    Jacobian class): materializes on first indexing; `J[:]` is the full
    matrix. Rows follow the flattened output, columns the flattened
    input."""

    def __init__(self, func, xs, is_batched=False):
        self._func, self._xs, self._is_batched = func, xs, is_batched
        self._mat = None

    def _materialize(self):
        if self._mat is None:
            self._mat = jacobian(self._func, self._xs,
                                 is_batched=self._is_batched)
        return self._mat

    @property
    def shape(self):
        return self._materialize().shape

    def __getitem__(self, idx):
        return self._materialize()[idx]

    def numpy(self):
        return self._materialize().numpy()


class Hessian(Jacobian):
    """Lazy Hessian view (reference Hessian class): symmetric (n, n) for a
    scalar objective."""

    def _materialize(self):
        if self._mat is None:
            self._mat = hessian(self._func, self._xs,
                                is_batched=self._is_batched)
        return self._mat


def forward_grad(func, xs, tangents=None):
    """Forward-mode derivative of `func` at `xs` seeded with `tangents`
    (default: ones). The reference routes this through its primitive
    forward-AD rules; here it is jax.jvp directly."""
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    f = _functionalize(func)
    primals = tuple(x.data for x in xs_l)
    if tangents is None:
        tans = tuple(jnp.ones_like(p) for p in primals)
    else:
        t_l = tangents if isinstance(tangents, (list, tuple)) else [tangents]
        tans = tuple(t.data if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in t_l)
    _, jv = jax.jvp(f, primals, tans)
    if isinstance(jv, tuple):
        out = tuple(Tensor(a) for a in jv)
        return out if len(out) > 1 else out[0]
    return Tensor(jv)


def grad(outputs, inputs, grad_outputs=None):
    """Reference incubate.autograd.grad: tape grad with create_graph
    semantics so the result composes into further differentiation."""
    return _tape_grad(outputs, inputs, grad_outputs=grad_outputs,
                      create_graph=True, allow_unused=True)
