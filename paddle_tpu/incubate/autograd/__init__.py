"""Higher-order autodiff extras (reference: python/paddle/incubate/
autograd/ — jacobian/hessian/jvp/vjp re-exported from the functional
autograd surface, which lowers to jax.jacfwd/jacrev/jvp/vjp)."""
from ...autograd.functional import (jacobian, hessian, vjp, jvp)  # noqa: F401

__all__ = ["jacobian", "hessian", "vjp", "jvp"]
