"""Higher-order autodiff extras (reference: python/paddle/incubate/autograd/).
Populated with jacobian/hessian."""
