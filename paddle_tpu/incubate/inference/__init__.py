"""paddle.incubate.inference (reference exposes inference utilities here)."""
from ...inference import Config, Predictor, create_predictor  # noqa: F401
