"""MoE (reference: python/paddle/incubate/distributed/models/moe/)."""
from .gate import (NaiveGate, SwitchGate, GShardGate, BaseGate,
                   topk_capacity_dispatch)
from .moe_layer import (MoELayer, ExpertMLP, global_scatter, global_gather)
