"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
— naive_gate.py, switch_gate.py, gshard_gate.py).

TPU-native form: gating must stay inside the traced graph with static
shapes, so routing is expressed as capacity-bucketed one-hot dispatch /
combine tensors ([tokens, experts, capacity]) rather than the reference's
variable-length index lists — the einsum over these is what XLA shards and
turns into the EP alltoall."""
import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import initializer as I


def _capacity(num_tokens, num_experts, top_k, capacity_factor):
    cap = int(capacity_factor * num_tokens * top_k / num_experts)
    return max(cap, 1)


def topk_capacity_dispatch(probs, top_k, capacity):
    """Build (combine [T,E,C], dispatch [T,E,C] bool, aux_loss) from router
    probabilities [T, E]. Iterative top-k with per-expert capacity: the i-th
    choice of each token lands at its cumulative position in the expert's
    buffer; overflow tokens are dropped (reference gshard semantics)."""
    T, E = probs.shape
    remaining = probs
    location_base = jnp.zeros((E,), dtype=jnp.int32)
    gates, ce_slots = [], []
    first_mask = None
    for i in range(top_k):
        idx = jnp.argmax(remaining, axis=1)                     # [T]
        mask = jax.nn.one_hot(idx, E, dtype=probs.dtype)        # [T,E]
        if first_mask is None:
            first_mask = mask
        pos = (jnp.cumsum(mask, axis=0) - 1
               + location_base[None, :]).astype(jnp.int32)      # [T,E]
        keep = (pos < capacity).astype(probs.dtype)
        mask = mask * keep
        location_base = location_base + mask.sum(axis=0).astype(jnp.int32)
        gates.append((probs * mask).sum(axis=1))                # [T]
        slot = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                              dtype=probs.dtype)                # [T,E,C]
        ce_slots.append(mask[..., None] * slot)
        remaining = remaining * (1.0 - jax.nn.one_hot(idx, E,
                                                      dtype=probs.dtype))
    denom = sum(gates)
    denom = jnp.where(denom > 0, denom, 1.0)
    combine = sum(g[:, None, None] / denom[:, None, None] * ce
                  for g, ce in zip(gates, ce_slots))
    dispatch = combine > 0
    # load-balance loss over first choices (gshard eq.(4) / switch eq.(4)):
    # E * sum_e f_e * P_e, minimized when routing is uniform
    f = first_mask.mean(axis=0)
    p = probs.mean(axis=0)
    aux_loss = E * jnp.sum(f * p)
    return combine, dispatch, aux_loss


class BaseGate(nn.Layer):
    def __init__(self, d_model, num_experts, top_k, capacity_factor=1.25):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            [d_model, num_experts], default_initializer=I.XavierNormal())

    def routing(self, x):
        """x [T, d] -> (combine [T,E,C], dispatch [T,E,C], aux_loss).
        Pure-jnp body: called inside the MoE layer's traced op."""
        raise NotImplementedError


class NaiveGate(BaseGate):
    """Top-k softmax routing, no jitter (reference naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2,
                 capacity_factor=1.25):
        super().__init__(d_model, num_expert * world_size, top_k,
                         capacity_factor)

    def routing(self, x, w):
        logits = x @ w
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        cap = _capacity(x.shape[0], self.num_experts, self.top_k,
                        self.capacity_factor)
        return topk_capacity_dispatch(probs, self.top_k, cap)


class SwitchGate(BaseGate):
    """Top-1 routing with multiplicative jitter during training
    (reference switch_gate.py; Switch-Transformer §2.2)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=1,
                 switch_eps=0.1, capacity_factor=1.25):
        super().__init__(d_model, num_expert * world_size, 1, capacity_factor)
        self.switch_eps = switch_eps

    def routing(self, x, w, rng_key=None):
        logits = x @ w
        if self.training and self.switch_eps > 0 and rng_key is not None:
            noise = jax.random.uniform(
                rng_key, logits.shape, minval=1.0 - self.switch_eps,
                maxval=1.0 + self.switch_eps)
            logits = logits * noise
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        cap = _capacity(x.shape[0], self.num_experts, 1,
                        self.capacity_factor)
        return topk_capacity_dispatch(probs, 1, cap)


class GShardGate(BaseGate):
    """Top-k (default 2) routing with capacity + load-balance loss
    (reference gshard_gate.py; GShard §3.2)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2,
                 capacity=(1.2, 2.4), group=None, capacity_factor=None):
        cf = capacity_factor if capacity_factor is not None else capacity[0]
        super().__init__(d_model, num_expert * world_size, top_k, cf)

    def routing(self, x, w):
        logits = x @ w
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        cap = _capacity(x.shape[0], self.num_experts, self.top_k,
                        self.capacity_factor)
        return topk_capacity_dispatch(probs, self.top_k, cap)
