"""MoE layer with expert parallelism (reference: python/paddle/incubate/
distributed/models/moe/moe_layer.py:261 — MoELayer with gate +
global_scatter/global_gather alltoall dispatch; spmd rules
paddle/phi/infermeta/spmd_rules/moe_gate_dispatch.cc, moe_combine.cc).

TPU-native mechanics: routing produces capacity-bucketed one-hot
dispatch/combine tensors (static shapes — XLA's requirement), and expert
computation is a batched einsum over an [E, ...] buffer. Two EP paths:

- **einsum/GSPMD** (default): the [E, C, d] buffer carries a sharding
  constraint on the expert dim; XLA inserts the alltoall pair
  (dispatch/combine) automatically — the compiler plays the role of the
  reference's global_scatter/global_gather ops.
- **explicit alltoall**: `global_scatter`/`global_gather` below are the
  shard_map + lax.all_to_all equivalents of the reference ops, for code
  that wants the collective placement spelled out.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

from .....framework.compat import shard_map
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.dispatch import apply_op
from paddle_tpu.core import random as _random
from paddle_tpu import nn
from paddle_tpu.nn import initializer as I
from .gate import NaiveGate, SwitchGate, GShardGate, BaseGate


def _ep_constraint(arr, mesh, axis_name):
    """Shard the leading (expert) dim over the EP axis inside the trace."""
    if mesh is None or axis_name is None:
        return arr
    spec = [None] * arr.ndim
    spec[0] = axis_name
    return lax.with_sharding_constraint(
        arr, jax.sharding.NamedSharding(mesh.jax_mesh, P(*spec)))


# ---------------------------------------------------------------------------
# explicit EP collectives (reference global_scatter/global_gather parity,
# capacity-padded: counts are implicit in the static [E, C, d] layout)
# ---------------------------------------------------------------------------
def global_scatter(x, group=None, mesh=None, axis_name=None):
    """Expert dispatch alltoall over the EP axis (P devices).

    Input [E, P*C, d]: expert-major buffers, capacity dim sharded so each
    source device holds its locally-routed [E, C, d] slots. Output has the
    same global shape but expert-sharded: each device ends up holding ALL
    devices' tokens for its E/P local experts. Reference: moe/global_scatter
    (variable-count alltoall); capacity padding makes the shapes static."""
    if group is not None:
        mesh, axis_name = group.mesh, group.axis_name
    jm = mesh.jax_mesh

    def impl(a):
        def local(v):  # [E, C, d] -> [E/P, P*C, d]
            return lax.all_to_all(v, axis_name, split_axis=0, concat_axis=1,
                                  tiled=True)
        return shard_map(local, mesh=jm, in_specs=P(None, axis_name),
                         out_specs=P(axis_name), check_vma=False)(a)
    return apply_op("global_scatter", impl, (x,), {})


def global_gather(x, group=None, mesh=None, axis_name=None):
    """Inverse of global_scatter: expert-sharded [E, P*C, d] back to
    capacity-sharded per-source buffers."""
    if group is not None:
        mesh, axis_name = group.mesh, group.axis_name
    jm = mesh.jax_mesh

    def impl(a):
        def local(v):  # [E/P, P*C, d] -> [E, C, d]
            return lax.all_to_all(v, axis_name, split_axis=1, concat_axis=0,
                                  tiled=True)
        return shard_map(local, mesh=jm, in_specs=P(axis_name),
                         out_specs=P(None, axis_name), check_vma=False)(a)
    return apply_op("global_gather", impl, (x,), {})


class ExpertMLP(nn.Layer):
    """Batched expert FFN: weights [E, d, ffn] / [E, ffn, d] so all experts
    run as one einsum on the MXU (and shard over the EP axis)."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden],
                                        default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model],
                                        default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([num_experts, 1, d_model],
                                        is_bias=True)
        self.activation = activation


class MoELayer(nn.Layer):
    """Mixture-of-experts layer (reference moe_layer.py:261).

    `experts` is either an ExpertMLP (batched, EP-shardable — preferred) or
    a LayerList of per-expert Layers (reference style; runs experts in a
    static python loop). The auxiliary load-balance loss of the last forward
    is exposed as `.l_aux` (a Tensor participating in autograd — add it to
    the training loss)."""

    def __init__(self, d_model=None, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, top_k=2,
                 num_experts=None, capacity_factor=1.25, mesh=None,
                 axis_name=None, **kwargs):
        super().__init__()
        if isinstance(gate, dict):  # reference config-dict form
            top_k = gate.get("top_k", top_k)
            gate_type = gate.get("type", "gshard")
            gate = None
        else:
            gate_type = "naive"
        if experts is None:
            raise ValueError("experts required (ExpertMLP or LayerList)")
        self.experts = experts
        if isinstance(experts, ExpertMLP):
            self.num_experts = experts.num_experts
        else:
            self.num_experts = len(experts)
        if d_model is None:
            if isinstance(experts, ExpertMLP):
                d_model = experts.w1.shape[1]
            elif gate is None:
                raise ValueError(
                    "d_model is required to build a gate when experts is a "
                    "LayerList (it cannot be inferred)")
        if gate is None:
            cls = {"naive": NaiveGate, "switch": SwitchGate,
                   "gshard": GShardGate}[gate_type]
            gate = cls(d_model, self.num_experts, top_k=top_k,
                       capacity_factor=capacity_factor)
        self.gate = gate
        if moe_group is not None:
            mesh, axis_name = moe_group.mesh, moe_group.axis_name
        self.mesh = mesh
        self.axis_name = axis_name
        self.l_aux = None

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        gate = self.gate
        mesh, axis_name = self.mesh, self.axis_name
        experts = self.experts
        batched = isinstance(experts, ExpertMLP)
        rng_key = _random.next_key() if isinstance(gate, SwitchGate) \
            and self.training else None

        if batched:
            act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
                   "silu": jax.nn.silu}[experts.activation]

            def impl(xf, gw, w1, b1, w2, b2):
                t = xf.reshape(-1, d)
                routing = functools.partial(gate.routing, t, gw)
                combine, dispatch, aux = routing(rng_key=rng_key) \
                    if rng_key is not None else routing()
                combine = combine.astype(xf.dtype)
                buf = jnp.einsum("tec,td->ecd",
                                 dispatch.astype(xf.dtype), t)
                buf = _ep_constraint(buf, mesh, axis_name)  # EP alltoall here
                h = act(jnp.einsum("ecd,edf->ecf", buf, w1) + b1)
                out = jnp.einsum("ecf,efd->ecd", h, w2) + b2
                out = _ep_constraint(out, mesh, axis_name)  # alltoall back
                y = jnp.einsum("tec,ecd->td", combine, out)
                return y.reshape(xf.shape), aux.astype(xf.dtype)

            y, aux = apply_op(
                "moe_layer", impl,
                (x, gate.weight, experts.w1, experts.b1, experts.w2,
                 experts.b2), {})
        else:
            # reference-style per-expert Layers: dispatch and combine are
            # traced ops; the experts themselves run as ordinary eager Layer
            # calls in between so their parameters stay on the tape
            def dispatch_impl(xf, gw):
                t = xf.reshape(-1, d)
                routing = functools.partial(gate.routing, t, gw)
                combine, dispatch, aux = routing(rng_key=rng_key) \
                    if rng_key is not None else routing()
                buf = jnp.einsum("tec,td->ecd",
                                 dispatch.astype(xf.dtype), t)
                return buf, combine.astype(xf.dtype), aux.astype(xf.dtype)

            buf, combine, aux = apply_op("moe_gate_dispatch", dispatch_impl,
                                         (x, gate.weight), {})
            outs = [experts[e](buf[e]) for e in range(self.num_experts)]

            def combine_impl(c, *eo):
                out = jnp.stack(eo, axis=0)
                return jnp.einsum("tec,ecd->td", c, out).reshape(x.shape)

            y = apply_op("moe_combine", combine_impl, (combine, *outs), {})
        self.l_aux = aux
        return y
