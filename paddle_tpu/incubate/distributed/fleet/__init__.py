"""paddle.incubate.distributed.fleet parity (reference
python/paddle/incubate/distributed/fleet/__init__.py: the recompute
entry points staged under incubate)."""
from ....distributed.fleet.recompute import (  # noqa: F401
    recompute_sequential, recompute_hybrid,
)

__all__ = ["recompute_sequential", "recompute_hybrid"]
