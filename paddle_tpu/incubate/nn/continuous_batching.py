"""Continuous-batching serving over the paged KV cache.

The vLLM-style serving loop the ROADMAP's "heavy traffic from millions of
users" regime needs: requests of wildly different lengths share one fixed
pool of cache blocks; a host-side free-list allocator hands blocks to
sequences as they grow and reclaims them the step a request finishes, and
every step runs ALL in-flight requests — some consuming whole CHUNKS of
their prompt (Sarathi-style chunked prefill under a per-step token
budget, so TTFT costs ceil(prompt/chunk) steps instead of prompt steps),
some mid-generation, some slots idle — as ONE compiled program
(FusedMultiTransformerEngine._paged_step over the ragged Pallas kernel,
ops/pallas/paged_attention.py).

Speculative multi-token decode rides the same query-span work list: a
model-free prompt-lookup proposer (`propose_draft_tokens` — match the
generated suffix's last n-gram against the prompt + everything emitted
so far, zero extra model passes) drafts up to `spec_k` continuation
tokens per decode slot; the scheduler grants those slots a 1+K span as
OPTIONAL FILLER after the mandatory decode-1 and prefill chunks, the
compiled step verifies the whole span in one pass (the ragged kernel's
intra-chunk causal mask makes position j's sample exactly the
sequential decode's choice), and the host accepts the longest matching
prefix — token-exact vs non-speculative greedy decoding by
construction. Rejected suffixes roll back through a paged-KV rewind
(host block free + `truncate_paged_kv_cache` zeroing), so the cache
stays bit-identical to a never-speculated one.

Host/device split: the allocator, block tables, lengths, and scheduling
live on the host (tiny int arrays, zero device round trips beyond the
step itself); the device program's shape is keyed only by the bucketed
work-list length, so admission and retirement never trigger recompiles
past the first few power-of-two buckets.

Resilience (ISSUE 11): the engine degrades instead of crashing.
Requests carry a priority class, optional step/wall deadlines, and can
be cancelled mid-flight; when an allocation or admission cannot be
satisfied the scheduler preempts the lowest-priority victim TO BLOCKS
(KV pages freed, request re-queued — with the prefix cache on, its
published blocks make re-prefill mostly a block-table copy) and
`kv_alloc_failure` is a per-request failure only when no victim
exists; pressure-aware admission sheds the lowest-priority queued work
when the SLO engine is burning budget or HBM headroom collapses. Every
request ends with a structured terminal status (`RequestResult`) in
`engine.finished`; survivors stay token-exact by construction (each
slot's tokens depend only on its own KV under greedy decoding).

Reference bar: vLLM's continuous batching scheduler + "Ragged Paged
Attention" (PAPERS.md); the reference framework's analogue is the
block_multihead_attention serving stack.
"""
import collections
import os
import time

import numpy as np

from ...observability import instrument as _metrics
from ...observability import tracing as _tracing
from ...ops.pallas.paged_attention import (RaggedWorkBuilder,
                                           build_ragged_work, default_pack,
                                           next_pow2)

__all__ = ["BlockAllocator", "GenerationRequest", "RequestResult",
           "KVAllocFailure", "ContinuousBatchingEngine",
           "propose_draft_tokens", "block_key", "prompt_block_keys"]


class KVAllocFailure(RuntimeError):
    """The KV pool (free list AND reuse pool) could not produce a
    block. A RuntimeError subclass so pre-existing `except
    RuntimeError` / pytest.raises(RuntimeError) callers keep working,
    but the engine's preemption/degradation backstop catches THIS type
    only — a device-side RuntimeError (XLA OOM, compile failure)
    escaping a compiled call must surface, not be misread as an
    allocation failure and silently demoted to a per-request error."""


def block_key(parent, tokens):
    """Chained content identity of one FULL cache block: structurally
    `(parent_key, tuple(token ids))`, root parent None. Nested tuples
    share structure with the parent key (O(1) extra memory per block)
    and compare by VALUE, so two requests that filled a block with the
    same tokens after the same prefix get the same key with zero
    hash-collision risk — the chain makes position implicit, so an
    identical token window at a different prefix depth gets a different
    key (its KV really is different: rope positions and attention
    context differ)."""
    return (parent, tuple(int(t) for t in tokens))


def prompt_block_keys(prompt_ids, block_size):
    """The chained key ladder of a prompt's FULL blocks — the same
    math admission hashes into ``req._prompt_keys``, exposed as a pure
    host-side function so a routing layer can compute a request's
    prefix identity WITHOUT an engine (the router matches this chain
    against each replica's published ``prefix_index_summary()``).
    Returns [] when the prompt doesn't cover one full block."""
    ks, k = [], None
    src = [int(t) for t in prompt_ids]
    for b in range(len(src) // block_size):
        k = block_key(k, src[b * block_size:(b + 1) * block_size])
        ks.append(k)
    return ks


def propose_draft_tokens(tokens, max_k, ngram=2):
    """Prompt-lookup (n-gram) draft proposal — the model-free speculative
    drafter: match the suffix's last `n` tokens (n = ngram down to 1)
    against every EARLIER position in `tokens` (prompt + generated), and
    propose the up-to-`max_k` tokens that followed the MOST RECENT match.
    Repetitive contexts (code, JSON, extraction, self-repeating greedy
    loops) hit constantly; zero model passes, zero state to shard.

    Host-side by design: pure python over the request's token list, the
    same place the scheduler already lives. Returns [] when nothing
    matches (the slot falls back to plain decode-1)."""
    if max_k <= 0:
        return []
    toks = list(tokens)
    n_tok = len(toks)
    for n in range(min(int(ngram), n_tok - 1), 0, -1):
        suffix = toks[n_tok - n:]
        # right-to-left: recency beats distance (the generated suffix is
        # a better predictor than a stale prompt occurrence)
        for start in range(n_tok - n - 1, -1, -1):
            if toks[start:start + n] == suffix:
                cont = toks[start + n:start + n + int(max_k)]
                if cont:
                    return cont
    return []


class BlockAllocator:
    """Refcounted free-list + content-addressed prefix index over the
    paged KV cache's physical blocks.

    Block ids [reserved, num_blocks) are allocatable; ids below `reserved`
    are parking space (idle batch slots point their table row at block 0
    so the one compiled step program can write SOMEWHERE harmless).

    Every held block carries a refcount: `alloc()` hands out rc=1,
    `share()`/`acquire()` bump it, `free()` decrements, and the block
    only leaves a request's hands when rc hits 0. A FULL, immutable
    block can be `register()`ed under its chained content key
    (`block_key`) into the hash->block index; a registered block whose
    refcount drops to 0 parks in an LRU reuse pool instead of the free
    list — still indexed, resurrectable by `acquire()` — and is only
    reclaimed (evicted from the index, oldest first) when the free list
    can't cover an `alloc()`. Allocation fails only when free list AND
    pool are both empty.

    Invariants (unit-tested directly): freeing a block nobody holds
    raises instead of corrupting the free list; `num_used` counts
    PHYSICAL blocks held by requests (pooled blocks are reusable cache,
    not in use) and is structurally non-negative; `high_water` tracks
    peak physical use — a block shared by 8 requests counts once."""

    # the exhaustion type, reachable from an allocator handle (fault
    # injectors raise `type(cb.allocator).OutOfBlocks` without an
    # import; the engine's degradation backstop catches exactly this)
    OutOfBlocks = KVAllocFailure

    # bounded prefix-index delta log: long enough to absorb every
    # register/evict between two consecutive summary refreshes on a
    # realistic workload; overflow just costs one full-walk rebuild
    INDEX_LOG = 128

    def __init__(self, num_blocks, reserved=1):
        if num_blocks <= reserved:
            raise ValueError(
                f"need more than {reserved} blocks (got {num_blocks})")
        self.num_blocks = num_blocks
        self.reserved = reserved
        self._free = list(range(num_blocks - 1, reserved - 1, -1))
        self._free_set = set(self._free)  # O(1) double-free check
        self._ref = {}          # block -> refcount, held blocks only
        self._index = {}        # block_key -> physical block (full blocks)
        self._key_of = {}       # registered block -> its key
        self._pool = collections.OrderedDict()  # rc==0 but reusable, LRU
        self.high_water = 0     # max PHYSICAL blocks ever in use at once
        self.evictions = 0      # pooled blocks reclaimed for fresh allocs
        self.index_epoch = 0    # bumps on every index add/remove
        self._index_log = collections.deque(maxlen=self.INDEX_LOG)

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_pooled(self):
        return len(self._pool)

    @property
    def num_available(self):
        """Blocks an alloc() can still produce: free list + reclaimable
        pool — what admission reservations must check against."""
        return len(self._free) + len(self._pool)

    @property
    def num_used(self):
        """PHYSICAL blocks held by requests (rc >= 1). Pooled blocks are
        cache, not use; a block shared by N requests counts once."""
        return (self.num_blocks - self.reserved) - len(self._free) \
            - len(self._pool)

    @property
    def num_shared(self):
        """Physical blocks referenced by more than one request."""
        return sum(1 for rc in self._ref.values() if rc > 1)

    @property
    def num_registered(self):
        """Blocks resident in the prefix index (held or pooled)."""
        return len(self._index)

    def refcount(self, b):
        return self._ref.get(b, 0)

    def _bump_high_water(self):
        if self.num_used > self.high_water:
            self.high_water = self.num_used

    def alloc(self):
        if self._free:
            b = self._free.pop()
            self._free_set.discard(b)
        elif self._pool:
            # reclaim the LRU-oldest reusable prefix block BEFORE
            # failing: cached history is worth strictly less than a
            # live request's next token
            b, key = self._pool.popitem(last=False)
            del self._index[key]
            del self._key_of[b]
            self.index_epoch += 1
            self._index_log.append((False, key))
            self.evictions += 1
            _metrics.prefix_cache_evictions().inc()
        else:
            _metrics.kv_alloc_failures().inc()
            raise KVAllocFailure("BlockAllocator: out of cache blocks")
        self._ref[b] = 1
        self._bump_high_water()
        return b

    def free(self, blocks):
        for b in blocks:
            if not (self.reserved <= b < self.num_blocks):
                raise ValueError(f"freeing out-of-pool block {b}")
            rc = self._ref.get(b, 0)
            if rc < 1:
                where = ("already on the free list"
                         if b in self._free_set else
                         "parked in the reuse pool" if b in self._pool
                         else "never allocated")
                raise ValueError(
                    f"freeing unallocated block {b} ({where})")
            if rc > 1:
                self._ref[b] = rc - 1
                continue
            del self._ref[b]
            key = self._key_of.get(b)
            if key is not None:
                # registered: park, newest at the LRU tail, still
                # indexed — acquire() resurrects, alloc() reclaims
                self._pool[b] = key
            else:
                self._free.append(b)
                self._free_set.add(b)

    def share(self, b):
        """One more holder of a live block (copy-on-write bookkeeping)."""
        if self._ref.get(b, 0) < 1:
            raise ValueError(f"sharing unallocated block {b}")
        self._ref[b] += 1
        return b

    def register(self, b, key):
        """Publish a held, FULL, immutable block under its content key.
        First writer wins: returns False (no-op) when the key is already
        indexed by another block or the block already carries a key."""
        if self._ref.get(b, 0) < 1:
            raise ValueError(f"registering unallocated block {b}")
        if key in self._index or b in self._key_of:
            return False
        self._index[key] = b
        self._key_of[b] = key
        self.index_epoch += 1
        self._index_log.append((True, key))
        return True

    def lookup(self, key):
        """Index probe without side effects: block id or None."""
        return self._index.get(key)

    def index_keys(self):
        """Snapshot of every content key currently resolvable by
        ``acquire()`` — held blocks AND pooled (freed-but-registered)
        ones. This is the prefix-index summary a routing layer
        publishes: a router matching a prompt's block-key chain against
        it knows exactly which leading blocks this allocator can map
        without a prefill sweep."""
        return frozenset(self._index)

    def index_delta_since(self, epoch):
        """Ordered ``(added, key)`` ops replaying the prefix index from
        `epoch` to ``index_epoch``, or None when the bounded log no
        longer reaches back that far (caller rebuilds from
        ``index_keys()``). Replay is order-sensitive: a key can leave
        the index (LRU reclaim) and re-enter under a new block."""
        n = self.index_epoch - epoch
        if n < 0 or n > len(self._index_log):
            return None
        if n == 0:
            return self.index_epoch, ()
        log = list(self._index_log)
        return self.index_epoch, tuple(log[len(log) - n:])

    def acquire(self, key):
        """Index hit -> the physical block with its refcount bumped
        (resurrected from the reuse pool when no request holds it);
        miss -> None."""
        b = self._index.get(key)
        if b is None:
            return None
        rc = self._ref.get(b, 0)
        if rc == 0:
            del self._pool[b]
            self._ref[b] = 1
            self._bump_high_water()
        else:
            self._ref[b] = rc + 1
        return b


class RequestResult(list):
    """Terminal record of one request in ``engine.finished``: the
    generated token list (it IS a list, so everything that compares
    ``finished[rid]`` against plain token lists keeps working) plus the
    structured status the resilience layer records. ``status`` is one
    of STATUSES; ``reason`` the machine-readable cause (e.g.
    ``kv_alloc_failure``, ``slo_burn``); ``preemptions`` how many times
    the request was preempted-and-resumed on the way here. A live
    request additionally passes through the transient ``preempted``
    status while it waits in the queue for re-admission."""

    STATUSES = ("finished", "cancelled", "deadline_exceeded", "failed",
                "shed", "rejected")

    def __init__(self, tokens=(), status="finished", reason=None,
                 preemptions=0):
        super().__init__(int(t) for t in tokens)
        if status not in self.STATUSES:
            raise ValueError(f"unknown terminal status {status!r} "
                             f"(have {self.STATUSES})")
        self.status = status
        self.reason = reason
        self.preemptions = int(preemptions)

    def __repr__(self):
        extra = f", reason={self.reason!r}" if self.reason else ""
        return (f"RequestResult({list.__repr__(self)}, "
                f"status={self.status!r}{extra})")


class GenerationRequest:
    """One serving request: prompt ids in, up to max_new_tokens out.

    Resilience knobs (all optional):

    * ``priority`` — scheduling class, 0 = most important (the
      default). Admission runs in (priority, arrival) order; when the
      KV pool can't satisfy an allocation or a higher-priority
      admission, the NEWEST request of the strictly-lowest priority is
      preempted to blocks; pressure shedding removes the lowest class
      first (never below the engine's ``shed_priority_min``).
    * ``deadline_steps`` / ``deadline_s`` — retire the request (status
      ``deadline_exceeded``, partial tokens kept) once that many engine
      steps / monotonic seconds have passed since submit, whether it is
      queued or mid-flight.
    * ``spec_k`` — per-request cap on speculative draft length, at most
      the engine's own ``spec_k`` (a larger value is a structured
      rejection at submit: the sample-gather width is engine-static).
    * ``temperature`` — must match the engine's temperature when given;
      per-request sampling is not supported and is rejected at submit
      instead of corrupting the batch mid-step.
    """

    _next_id = 0

    def __init__(self, prompt_ids, max_new_tokens, request_id=None,
                 priority=0, deadline_steps=None, deadline_s=None,
                 spec_k=None, temperature=None):
        self.prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not self.prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.max_new_tokens = int(max_new_tokens)
        if request_id is None:
            request_id = GenerationRequest._next_id
            GenerationRequest._next_id += 1
        elif isinstance(request_id, int) and not isinstance(request_id, bool) \
                and request_id >= GenerationRequest._next_id:
            # a user-supplied int id RESERVES the auto counter past it, so
            # a later auto-assigned id can never silently collide with it
            GenerationRequest._next_id = request_id + 1
        self.request_id = request_id
        self.priority = int(priority)
        if self.priority < 0:
            raise ValueError("priority must be >= 0 (0 = most important)")
        self.deadline_steps = None if deadline_steps is None \
            else int(deadline_steps)
        if self.deadline_steps is not None and self.deadline_steps < 1:
            raise ValueError("deadline_steps must be >= 1")
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        self.spec_k = None if spec_k is None else int(spec_k)
        if self.spec_k is not None and self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        self.temperature = None if temperature is None \
            else float(temperature)
        if self.temperature is not None and self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        # lifecycle status: new -> queued -> running -> terminal
        # (RequestResult.STATUSES), with the transient `preempted`
        # between running and re-queued
        self.status = "new"
        self.status_reason = None
        self.preemptions = 0
        self._cancel = False    # processed at the next retire pass
        self._seq = None        # submission order (admission tie-break)
        self._admit_seq = None  # admission order (victim tie-break)
        self._submit_step = None
        # runtime state (owned by the engine)
        self.blocks = []        # physical cache blocks, in table order
        self.progress = 0       # prompt tokens consumed so far
        self.generated = []
        # prefill source/target: for a fresh request the prompt itself;
        # a preempted-and-resumed request re-prefills prompt + every
        # token it already emitted (the KV it lost), then decodes on
        self._prefill_src = self.prompt
        self._resume_len = len(self.prompt)
        # speculative-decode acceptance bookkeeping (engine-owned):
        # drafts proposed for / accepted by this request's verification
        self.spec_drafted = 0
        self.spec_accepted = 0
        # prefix-cache bookkeeping (engine-owned): prompt tokens whose KV
        # was MAPPED from shared blocks instead of prefilled, the chain
        # key after the blocks registered/matched so far, and how many
        # leading blocks that chain covers
        self.cached_prefix = 0
        self._prefix_key = None
        self._prompt_keys = None    # chained key per full prompt block
        self._registered = 0
        self._miss_frontier = -1    # last prompt position a miss counted at
        self._cow_reserve = 0       # shared blocks this request may yet COW
        # latency bookkeeping (host monotonic clock; set by the engine)
        self.submit_time = None
        self.admit_time = None
        self.first_token_time = None
        self._last_token_time = None
        # span timebase (perf_counter — the tracing/profiler clock, a
        # DIFFERENT epoch from time.monotonic above)
        self._submit_pc = None

    @property
    def done(self):
        return len(self.generated) >= self.max_new_tokens

    def total_tokens(self):
        return len(self.prompt) + self.max_new_tokens

    def blocks_needed(self, block_size):
        return -(-self.total_tokens() // block_size)


class ContinuousBatchingEngine:
    """Per-step admission / retirement scheduler over a
    FusedMultiTransformerEngine's paged decode mode.

    Each step():
      1. retire finished requests (free their blocks — eviction),
      2. admit queued requests into idle slots (FIFO; a request is only
         admitted when the free list can cover its WORST-CASE footprint,
         so no in-flight request can ever starve mid-generation),
      3. fill the per-step TOKEN BUDGET (Sarathi-style chunked prefill):
         decode-phase slots are mandatory at one token each, then the
         remaining budget is spent on prompt CHUNKS of up to
         `prefill_chunk` tokens from prefill-phase slots in slot order —
         a 512-token prompt costs ceil(512/chunk) steps, not 512,
      4. grow each active sequence's block list to cover the tokens the
         step appends (a chunk may cross several block boundaries),
      5. run one compiled step over all slots: the whole mixed
         prefill+decode batch advances in ONE program over the ragged
         Pallas kernel, and each slot samples from its chunk's last
         valid position.

    Greedy sampling (temperature 0) by default; temperature/top_p thread
    straight through to the engine's fused sampler.

    `prefill_chunk=1` reproduces the PR-1 one-token-per-step prefill
    exactly; `token_budget=None` means unthrottled (every prefill slot
    gets a full chunk each step). Chunking is token-exact either way.

    `spec_k > 0` turns on speculative multi-token decode (greedy only):
    each decode slot may be granted up to `spec_k` prompt-lookup draft
    tokens on top of its mandatory decode-1 — drafts are optional
    FILLER, granted only after every decode token and prompt chunk fit
    the budget — and the compiled step verifies the whole 1+K span in
    one pass. Accepted prefixes emit several tokens per step; rejected
    suffixes rewind the paged cache (block free + device-side zeroing),
    so generations stay token-exact vs `spec_k=0` and vs
    `engine.generate()`.

    `tpot_slo` (seconds, optional) arms the latency-SLO chunk
    controller: when the rolling mean of decode time-per-output-token
    exceeds the SLO, `prefill_chunk` shrinks one power-of-two bucket
    (never below `min_prefill_chunk`) — trading TTFT headroom for
    decode latency under load, the ROADMAP's "next scheduler lever".

    `prefix_cache=True` turns on automatic prefix caching: every FULL
    block a request commits (prompt or generated tokens) is published
    into a content-addressed index (`block_key` chains), and an
    incoming request's prompt is matched against it block by block —
    hits map the shared physical block straight into the block table
    and the scheduler only grants prefill chunks for the uncached
    suffix, so N requests sharing a system prompt pay ONE chunk sweep
    over it. Matching re-runs each step while a slot is mid-prefill
    (wavefront: a follower maps each block the step after its leader
    registers it) and the scheduler defers a slot whose next block an
    earlier slot is computing THIS step, so even concurrently-submitted
    duplicates dedup. Writes into a block other requests still read
    trigger copy-on-write (`_cow_block`); retired requests' registered
    blocks park in an LRU reuse pool that serves conversation-resume
    hits until the free list runs dry. Token-exact by construction:
    mapped KV is the same KV the request would have computed. Block-
    table contents are data, not shape — the bucketed (work-list,
    chunk-width) compile keys are untouched. Default OFF: the committed
    serving baselines predate the reuse pool's effect on the free-list
    gauges.

    `monitor` (optional, observability/slo.SLOMonitor) attaches the
    serving SLO engine: every step() ends with a host-side
    `monitor.tick()` — on the monitor's cadence that samples the
    metrics registry into windowed time-series rings and evaluates the
    declared objectives' multi-window burn rates (a breach counts into
    slo_breaches_total, lands on the timeline, and fires the flight
    recorder's `slo_burn_rate` trigger). Pure host math: token-exact-
    neutral with zero effect on the compile-bucket keyspace.

    `memory_watch` (optional, observability/memory.MemoryMonitor) is
    the device-resource counterpart: the same end-of-step tick()
    cadence drives HBM/census accounting gauges and the `hbm_pressure`
    flight trigger when headroom drops below the monitor's threshold —
    the OOM black box, armed next to the SLO engine. Host-side only,
    token-exact-neutral by the same construction.

    Resilience (ISSUE 11): requests carry a priority class and optional
    deadlines, `cancel()` retires them mid-flight through the normal
    block-free path, and allocation/admission pressure preempts the
    newest strictly-lower-priority victim TO BLOCKS (KV freed, request
    re-queued; with the prefix cache on its published blocks make
    re-prefill mostly a block-table copy, and resumption is token-exact
    under greedy decoding because each slot's tokens depend only on its
    own KV). `kv_alloc_failure` is a per-request failure — dump,
    structured `failed` status, serving continues — only when no victim
    exists. `shed_on_pressure=True` additionally lets the admission
    gate shed the lowest-priority queued class (priority >=
    `shed_priority_min`) while the attached SLO monitor reports burn-
    rate breaches or the memory watch reports HBM pressure. Every
    terminal path records a `RequestResult` (a list of the generated
    tokens + `status`/`reason`/`preemptions`) in `engine.finished`.
    All of it is host-side scheduling: work-list/slab shapes stay on
    the same bucketed compile treadmill, and default-config behavior
    (priority 0, no deadlines, shedding off) is bit-identical to the
    pre-resilience engine.

    Tensor-parallel serving: hand in an engine built with ``tp > 1``
    and the SAME scheduler drives the whole device mesh — admission,
    chunk budgeting, spec accept/rewind, prefix matching, and
    preemption all compute once on the host and dispatch one
    shard_map'd step program (the paged KV cache and the ragged kernel
    shard over kv-heads; inference/tp_layout.py). The bucketed
    (work-list length, chunk width) compile keys are untouched — zero
    new buckets after warmup holds per mesh shape — and the scheduler
    additionally records the step's collective payload
    (``collective_bytes_total{op="psum",axis="tp"}`` + a ``collective``
    timeline span) and per-device KV-bytes gauges (1/tp of the
    single-chip figure by construction). Token-exact vs the tp=1
    engine in every mode, pinned by tests/test_serve_tp.py and the
    serve_bench --tp gate.
    """

    SLO_WINDOW = 8      # decode-TPOT samples per controller decision

    def __init__(self, engine, num_blocks, block_size, max_batch=8,
                 temperature=0.0, top_p=1.0, seed=0, prefill_chunk=64,
                 token_budget=None, spec_k=0, spec_ngram=2,
                 tpot_slo=None, min_prefill_chunk=64, prefix_cache=False,
                 monitor=None, memory_watch=None, shed_on_pressure=False,
                 shed_priority_min=1, autotune_cache=None,
                 host_fastpath=True, host_debug_check=False,
                 overlap_fetch=False):
        import jax

        self.engine = engine
        self.block_size = int(block_size)
        self.max_batch = int(max_batch)
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.token_budget = None if token_budget is None \
            else int(token_budget)
        if self.token_budget is not None and self.token_budget < 1:
            raise ValueError("token_budget must be >= 1")
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        if self.spec_k and float(temperature) > 0.0:
            # greedy verification accepts drafts that MATCH the argmax;
            # sampled decoding needs rejection sampling to stay unbiased
            # — not implemented, so refuse loudly instead of skewing the
            # output distribution
            raise ValueError(
                "speculative decoding (spec_k > 0) is greedy-only: "
                "temperature must be 0")
        self.spec_ngram = int(spec_ngram)
        if self.spec_k and self.spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        if self.spec_k:
            # pin the acceptance-length histogram's bucket range to this
            # engine's spec_k (buckets bind on first creation)
            _metrics.spec_accept_len(max(8, self.spec_k))
        self.tpot_slo = None if tpot_slo is None else float(tpot_slo)
        if self.tpot_slo is not None and self.tpot_slo <= 0:
            raise ValueError("tpot_slo must be > 0 seconds")
        self.min_prefill_chunk = int(min_prefill_chunk)
        self._tpot_window = collections.deque(maxlen=self.SLO_WINDOW)
        self.max_blocks = engine.max_seq_len // self.block_size
        if self.max_blocks < 1:
            raise ValueError("block_size larger than engine.max_seq_len")
        self.allocator = BlockAllocator(num_blocks)
        self.caches = engine.new_paged_caches(num_blocks, self.block_size)
        self.tables = np.zeros((self.max_batch, self.max_blocks), np.int32)
        self.lens = np.zeros(self.max_batch, np.int32)
        self.slots = [None] * self.max_batch
        self.queue = collections.deque()
        self.finished = {}
        self._ids = set()       # queued + active ids: O(1) duplicate check
        self._temp = float(temperature)
        self._topp = float(top_p)
        self._key = jax.random.PRNGKey(int(seed))
        self._step_count = 0
        # padded work-list lengths already compiled for: the work list's
        # static length keys the decode program, so a length outside this
        # set means admission just caused an XLA recompile — the exact
        # event the "no recompiles past the first few buckets" contract
        # forbids in steady state. Counted per bucket so a test (and a
        # dashboard) can assert the counter stays flat.
        self._seen_buckets = set()
        # declare_warm() flips this: a fresh bucket AFTER that is the
        # anomaly the flight recorder dumps on (admission recompiled)
        self._warm = False
        self._sched_info = {}
        # automatic prefix caching: content-addressed COW sharing of
        # full prompt/generation blocks across requests. OFF by default:
        # the committed serving baselines (step counts, free-pool
        # gauges) predate the reuse pool and must stay byte-stable.
        self._prefix_on = bool(prefix_cache)
        self._pending_stalls = set()
        # engine-local mirror of the prefix-cache counters (the process
        # registry aggregates across engines; tests and the bench want
        # THIS engine's numbers)
        self.cache_stats = {"hit_blocks": 0, "miss_blocks": 0,
                            "cow_copies": 0}
        # SLO monitor (observability/slo.SLOMonitor or anything with a
        # host-side tick()): sampled on a cadence from the end of every
        # step — pure host math over the registry, so it is token-exact-
        # neutral and touches no compile key by construction
        self.monitor = monitor
        # HBM/census accounting on the same tick cadence (memory.py
        # MemoryMonitor): gauges + the hbm_pressure flight trigger
        self.memory_watch = memory_watch
        # pressure-aware admission (OFF by default: the committed serve
        # baselines predate shedding): when the attached SLO monitor's
        # last evaluation breached, or the memory watch reported HBM
        # pressure, the admission gate sheds the lowest-priority queued
        # class (never below shed_priority_min — priority-0 work is not
        # sheddable by default) as a STRUCTURED rejection, before the
        # pool exhausts and preemption has to do it the hard way
        self.shed_on_pressure = bool(shed_on_pressure)
        self.shed_priority_min = int(shed_priority_min)
        if self.shed_priority_min < 0:
            raise ValueError("shed_priority_min must be >= 0")
        self._submit_counter = 0
        self._admit_counter = 0
        # tensor-parallel serving (engine built with tp > 1): the
        # scheduler stays a single host-side brain — every decision
        # above computes once and drives ONE shard_map'd mesh program —
        # but the step dispatch gains collective telemetry (the two
        # row-parallel psums per layer, attributed analytically through
        # the PR-9 comm-task path) and the pool gauges gain a
        # per-device bytes view (each device holds 1/tp of every
        # block's kv heads). tp == 1 leaves ALL of it dormant: the
        # committed single-chip baselines stay byte-stable.
        self._tp = int(getattr(engine, "tp", 1) or 1)
        self._comm_seconds = {}     # request id -> comm-window seconds
        self._comm_tasks = None
        if self._tp > 1:
            from ...distributed.comm_watchdog import comm_task_manager
            self._comm_tasks = comm_task_manager
            self._kv_dev_block_bytes = engine.kv_device_block_bytes(
                self.block_size)
            _metrics.serve_tp_degree().set(self._tp)
        # streaming fanout (ISSUE 12, the serving gateway's engine-side
        # half): host-side emission hooks, fired on the stepper thread.
        # `on_token(request_id, tokens, step)` fires for every committed
        # emission — the first token a finished prefill samples, and each
        # verified decode span (token + accepted drafts) — AFTER the
        # accept/rewind settled, so a hooked consumer never sees a token
        # the engine later takes back. `on_terminal(request_id, result)`
        # fires exactly once per request, whenever a RequestResult lands
        # in `finished` (finish/cancel/deadline/failure/shed/reject).
        # Pure host callbacks on host data: token-exact-neutral with
        # zero effect on the compile-bucket keyspace by construction.
        # Hooks must not raise — an exception propagates into step() (or
        # submit()) like any scheduler bug would.
        self.on_token = None
        self.on_terminal = None
        kvh = self.caches[0].shape[1]
        num_q = engine.num_heads
        self._pack = default_pack(self.max_batch, num_q // kvh)
        # committed autotune winners (ops/pallas/autotune.py): passing a
        # cache (path or dict) opts the scheduler into the swept
        # (pack, prefill_chunk) for this EXACT shape class — resolved
        # once here, zero per-step host cost. The tuned chunk comes out
        # of the sweep's pow2 candidate family, so the warmup treadmill
        # covers the same (t_total, c) compile buckets it always did; a
        # missing/stale/foreign cache degrades to the defaults above,
        # never raises (the committed serving baselines run untuned).
        if autotune_cache is not None:
            from ...ops.pallas import autotune as _autotune
            cache_d = _autotune.load_serve_cache(autotune_cache)
            cfg = None
            if cache_d is not None:
                cfg = _autotune.serve_winner(
                    cache_d, _autotune.serve_shape_class(
                        kvh, num_q // kvh, self.block_size,
                        engine.head_dim,
                        getattr(engine, "_dtype", "float32")))
            if cfg is not None:
                self._pack = max(1, min(int(cfg["pack"]),
                                        self.max_batch))
                self.prefill_chunk = max(1, int(cfg["prefill_chunk"]))
        # host fast path (ISSUE 20): incremental work lists + in-place
        # step inputs. Built AFTER autotune so the builder bakes in the
        # final pack. ON by default — every array it hands the compiled
        # step is elementwise identical to the from-scratch build (the
        # committed serving baselines stay byte-stable); OFF keeps the
        # legacy per-step-rebuild path alive as the reference the debug
        # cross-check and the host bench leg compare against.
        self._host_fastpath = bool(host_fastpath)
        self._host_debug = bool(host_debug_check) or bool(
            os.environ.get("PADDLE_TPU_HOST_DEBUG_CHECK"))
        # overlap is OPT-IN: it reorders token-independent host
        # bookkeeping (non-completing prefill advancement, stall
        # events, monitor/memory ticks) to before the token fetch, so
        # tick cadence sees last step's samples — token-exact (pinned
        # by serve_bench --host in every mode), but not span/metric-
        # order-identical, hence not the default
        self._overlap_fetch = bool(overlap_fetch)
        self._work_builder = RaggedWorkBuilder(
            self.max_batch, self.max_blocks, self.block_size,
            self._pack) if self._host_fastpath else None
        # persistent step-input buffers, keyed by the same bucketed
        # widths that key the compiles — steady state allocates nothing
        self._slab_bufs = {}        # c -> [B, c] int32
        self._sel_bufs = {}         # w_sel -> [B, w_sel] int32
        self._q_arr_buf = np.zeros(self.max_batch, np.int32)
        self._attn_buf = np.zeros(self.max_batch, np.int32)
        self._rw_old_buf = np.zeros(self.max_batch, np.int32)
        self._ztab_buf = None       # lazily: only prefix-on rewinds
        self._input_copy_bytes = 0  # engine-local mirror of the counter
        self._overlap_steps = 0
        self._last_host_phases = {}
        self._wb_last = (0, 0, 0, 0)    # registry-mirrored builder state

    def host_stats(self):
        """Engine-local host-fast-path accounting (the process registry
        aggregates across engines; tests and serve_bench want THIS
        engine's numbers): work-segment reuse/rebuild and assembly-mode
        counts from the work-list builder, step-input copy bytes,
        overlap-mode step count, and the last step's host-phase split
        in seconds."""
        wb = self._work_builder
        return {
            "fastpath": self._host_fastpath,
            "overlap": self._overlap_fetch,
            "segments_reused": wb.segments_reused if wb else 0,
            "segments_rebuilt": wb.segments_rebuilt if wb else 0,
            "assemblies_full": wb.assemblies_full if wb else 0,
            "assemblies_incremental":
                wb.assemblies_incremental if wb else 0,
            "input_copy_bytes": self._input_copy_bytes,
            "overlap_steps": self._overlap_steps,
            "phases": dict(self._last_host_phases),
        }

    # -- scheduling ---------------------------------------------------------

    def submit(self, request):
        # table capacity, NOT max_seq_len: when max_seq_len is not a
        # block multiple the table floor-divides down and the last
        # partial block's tokens are unreachable
        capacity = self.max_blocks * self.block_size
        if request.total_tokens() > capacity:
            raise ValueError(
                f"request {request.request_id}: {request.total_tokens()} "
                f"tokens exceeds the block-table capacity {capacity} "
                f"({self.max_blocks} blocks x {self.block_size})")
        if request.blocks_needed(self.block_size) > \
                self.allocator.num_blocks - self.allocator.reserved:
            raise ValueError(
                f"request {request.request_id} can never fit: needs "
                f"{request.blocks_needed(self.block_size)} blocks, pool "
                f"has {self.allocator.num_blocks - self.allocator.reserved}")
        rid = request.request_id
        # O(1): the live-id set tracks queued + active, `finished` keeps
        # the retired ones — no linear scan per submit
        if rid in self._ids or rid in self.finished:
            raise ValueError(f"duplicate request_id {rid}")
        # unsupported CONFIG combos are a structured per-request
        # rejection, not an exception: the caller that would have hit a
        # mid-step raise (or a silently skewed output distribution)
        # gets a terminal record instead, and the serve loop never sees
        # the bad request at all
        reason = self._reject_reason(request)
        if reason is not None:
            request.status = "rejected"
            request.status_reason = reason
            res = RequestResult((), status="rejected", reason=reason)
            self.finished[rid] = res
            _metrics.serve_rejected().labels(reason=reason).inc()
            _tracing.get_tracer().event(
                "reject", request=rid, status="rejected", reason=reason)
            if self.on_terminal is not None:
                self.on_terminal(rid, res)
            return "rejected"
        request.submit_time = time.monotonic()
        request._submit_pc = time.perf_counter()
        request._submit_step = self._step_count
        request._seq = self._submit_counter
        self._submit_counter += 1
        request.status = "queued"
        self.queue.append(request)
        self._ids.add(rid)
        _metrics.serve_queue_depth().set(len(self.queue))
        _tracing.get_tracer().event(
            "submit", request=rid, prompt_tokens=len(request.prompt),
            max_new_tokens=request.max_new_tokens,
            priority=request.priority)
        return "queued"

    def _reject_reason(self, request):
        """Submission-time screen for per-request knobs the engine
        cannot honor mid-flight. Reasons are a small FIXED label set
        (they feed a labeled counter — the GL112 contract)."""
        if request.temperature is not None \
                and request.temperature != self._temp:
            # the fused sampler takes ONE batch temperature; honoring a
            # different per-request value would re-key the compiled
            # step or skew every other slot's sampling stream
            return "temperature_override"
        # past this point any per-request temperature EQUALS the
        # engine's, so the speculation check reads the engine's
        k_req = request.spec_k
        if (k_req or 0) > 0 and self._temp > 0.0:
            # greedy verification only (engine-level spec_k>0 + temp>0
            # is already refused at construction; this is the
            # per-request echo of the same contract: speculation asked
            # of a sampling engine)
            return "spec_sampled"
        if k_req is not None and k_req > self.spec_k:
            # the sample-gather width W = 1 + engine.spec_k is static
            # per compiled bucket: a wider per-request span cannot be
            # verified without a fresh compile keyspace
            return "spec_k_exceeds_engine"
        return None

    @property
    def num_active(self):
        return sum(r is not None for r in self.slots)

    @property
    def tp(self):
        """Tensor-parallel width of the underlying engine's mesh."""
        return self._tp

    def device_kv_report(self):
        """Per-device paged-KV accounting for the mesh-aware health
        surfaces (gateway /healthz, serve_monitor --scrape): one row
        per device with its kv-head-shard byte figures. Single-chip
        engines report one device whose block bytes cover ALL kv
        heads, so the shape is uniform for consumers."""
        if self._tp > 1:
            per_block = self._kv_dev_block_bytes
        else:
            fn = getattr(self.engine, "kv_device_block_bytes", None)
            per_block = fn(self.block_size) if fn is not None else 0
        return [{
            "device": d,
            "kv_bytes_used": self.allocator.num_used * per_block,
            "kv_bytes_high_water": self.allocator.high_water * per_block,
            "kv_blocks_used": self.allocator.num_used,
        } for d in range(self._tp)]

    def prefix_index_summary(self):
        """The prefix-routing summary this replica publishes: the
        frozenset of chained block keys its allocator can currently
        map without a prefill sweep (empty when prefix caching is
        off). Read on the stepper thread that owns the engine — the
        router refreshes its cached copy from terminal fanout, which
        runs on exactly that thread."""
        if not self._prefix_on:
            return frozenset()
        return self.allocator.index_keys()

    def prefix_index_version(self):
        """Monotonic version of :meth:`prefix_index_summary`: bumps on
        every index add/evict. Pinned at 0 when prefix caching is off
        (the summary is the constant empty set)."""
        return self.allocator.index_epoch if self._prefix_on else 0

    def prefix_index_delta(self, since_version):
        """Incremental complement to :meth:`prefix_index_summary`: the
        new version plus the ordered ``(added, key)`` ops since
        `since_version`, or None when the allocator's bounded delta
        log has aged out (the caller falls back to the full summary
        walk). Same thread contract as the summary."""
        if not self._prefix_on:
            return 0, ()
        return self.allocator.index_delta_since(since_version)

    def _deadline_passed(self, req, now=None):
        if req.deadline_steps is not None \
                and req._submit_step is not None \
                and self._step_count - req._submit_step \
                >= req.deadline_steps:
            return True
        if req.deadline_s is not None and req.submit_time is not None:
            now = time.monotonic() if now is None else now
            if now - req.submit_time >= req.deadline_s:
                return True
        return False

    def _count_input_bytes(self, n):
        # the legacy per-step-rebuild path's copy bill: bytes freshly
        # allocated for compiled-step inputs. The fast path never calls
        # this — the "copy bytes drop to 0" half of the ISSUE-20 gate.
        self._input_copy_bytes += int(n)
        _metrics.serve_input_copy_bytes().inc(int(n))

    def _check_host_state(self, attn_lens, q_arr, work, t_total, pack):
        """Debug cross-check (host_debug_check=True, or the
        PADDLE_TPU_HOST_DEBUG_CHECK env var): the incremental work list
        must equal a from-scratch `build_ragged_work` over the same
        persistent tables/lens, elementwise including padding. A
        mismatch means a table-writing site forgot `_dirty_slot` — fail
        the step loudly instead of serving a stale block mapping."""
        ref, _, rtot, rpack = build_ragged_work(
            self.tables, attn_lens, self.block_size, self._pack,
            bucket_to=next_pow2, q_lens=q_arr)
        if rtot != t_total or rpack != pack or not all(
                np.array_equal(a, b) for a, b in zip(ref, work)):
            raise AssertionError(
                "host fast path diverged from the from-scratch "
                f"work-list rebuild at step {self._step_count}: a "
                "block-table mutation site is missing its _dirty_slot "
                "mark")

    def _dirty_slot(self, i):
        # slot i's block-table row just changed: its cached work-list
        # segment is stale. Every table-writing site funnels through
        # here (admit / prefix match / COW / grow / rewind / preempt /
        # retire) — the dirty-slot schedule the host bench leg pins.
        if self._work_builder is not None:
            self._work_builder.mark_dirty(i)

    def _finish_slot(self, i, status, reason=None):
        """Terminal retirement of slot i, whatever the cause: free its
        KV (registered blocks park in the prefix pool — the ISSUE-5
        rewind/free discipline; shared blocks just decref), clear the
        table row, and record the structured RequestResult. Every
        terminal path funnels through here so the allocator bookkeeping
        can't diverge between finish/cancel/deadline/failure."""
        req = self.slots[i]
        self.allocator.free(req.blocks)
        req.blocks = []
        self.slots[i] = None
        self.tables[i] = 0
        self.lens[i] = 0
        self._dirty_slot(i)
        req.status = status
        req.status_reason = reason
        res = RequestResult(
            req.generated, status=status, reason=reason,
            preemptions=req.preemptions)
        # comm attribution moves onto the terminal record: the live
        # dict must not grow one entry per request forever (explain()
        # falls back to the RequestResult after retirement)
        res.comm_s = self._comm_seconds.pop(req.request_id, 0.0)
        self.finished[req.request_id] = res
        self._ids.discard(req.request_id)
        _tracing.get_tracer().event(
            "retire", request=req.request_id, status=status,
            generated=len(req.generated),
            spec_drafted=req.spec_drafted,
            spec_accepted=req.spec_accepted)
        if self.on_terminal is not None:
            self.on_terminal(req.request_id, res)

    def _terminal_queued(self, req, status, reason=None):
        """Terminal record for a request that never (re)entered a slot
        this round: queued cancel/deadline/shed. Holds no blocks by
        construction (a preempted request gave its blocks back when it
        left its slot), so this is pure bookkeeping."""
        req.status = status
        req.status_reason = reason
        res = RequestResult(
            req.generated, status=status, reason=reason,
            preemptions=req.preemptions)
        res.comm_s = self._comm_seconds.pop(req.request_id, 0.0)
        self.finished[req.request_id] = res
        self._ids.discard(req.request_id)
        _metrics.serve_queue_depth().set(len(self.queue))
        if self.on_terminal is not None:
            self.on_terminal(req.request_id, res)

    def _retire(self):
        retired = 0
        now = time.monotonic()
        tr = _tracing.get_tracer()
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if req.done:
                self._finish_slot(i, "finished")
                _metrics.serve_requests_total().inc()
                retired += 1
            elif req._cancel:
                _metrics.serve_cancelled().inc()
                tr.event("cancel", request=req.request_id,
                         status="cancelled",
                         generated=len(req.generated))
                self._finish_slot(i, "cancelled")
                retired += 1
            elif self._deadline_passed(req, now):
                _metrics.serve_deadline_exceeded().inc()
                tr.event("deadline_exceeded", request=req.request_id,
                         status="deadline_exceeded",
                         generated=len(req.generated),
                         deadline_steps=req.deadline_steps)
                self._finish_slot(i, "deadline_exceeded", "in_flight")
                retired += 1
        if retired:
            self._update_pool_gauges()

    def cancel(self, request_id):
        """Retire a request mid-flight. A queued request (including a
        preempted one awaiting re-admission) leaves immediately; an
        active request is flagged and retired at the top of the next
        step — its KV blocks go back to the pool through the same free
        path as normal retirement, so mid-speculation or mid-prefill
        state is reclaimed exactly. Terminal status `cancelled`, with
        whatever tokens were already generated. Returns True when the
        request was found live, False when it is unknown or already
        terminal. Host-thread API: call between steps, like submit()."""
        for req in self.queue:
            if req.request_id == request_id:
                self.queue.remove(req)
                _metrics.serve_cancelled().inc()
                _tracing.get_tracer().event(
                    "cancel", request=request_id, status="cancelled",
                    generated=len(req.generated))
                self._terminal_queued(req, "cancelled")
                return True
        for req in self.slots:
            if req is not None and req.request_id == request_id:
                req._cancel = True
                return True
        return False

    def _update_pool_gauges(self):
        _metrics.kv_blocks_free().set(self.allocator.num_free)
        _metrics.kv_blocks_used().set(self.allocator.num_used)
        _metrics.kv_blocks_high_water().set(self.allocator.high_water)
        _metrics.serve_inflight().set(self.num_active)
        _metrics.serve_queue_depth().set(len(self.queue))
        if self._prefix_on:
            _metrics.kv_blocks_shared().set(self.allocator.num_shared)
            _metrics.kv_blocks_prefix_resident().set(
                self.allocator.num_registered)
        if self._tp > 1:
            # per-device bytes view of the same pool: the allocator is
            # one flat host-side block-id space, every device holds the
            # kv-head shard of every block, so the per-device figures
            # are symmetric by construction — surfaced per device so
            # the mesh dashboard (serve_monitor --scrape, /healthz)
            # shows the fleet, not a silently-device-0 number
            used = _metrics.kv_device_bytes_used()
            hw = _metrics.kv_device_bytes_high_water()
            used_b = self.allocator.num_used * self._kv_dev_block_bytes
            hw_b = self.allocator.high_water * self._kv_dev_block_bytes
            for d in range(self._tp):   # bounded by mesh topology
                used.labels(device=str(d)).set(used_b)
                hw.labels(device=str(d)).set(hw_b)

    def _admission_pressure(self):
        """Shed signal for the admission gate: the attached SLO
        monitor's last burn-rate evaluation breached (PR 8), or the
        memory watch reported HBM pressure (PR 9). Returns the fixed
        reason label, or None when admission should run normally."""
        if not self.shed_on_pressure:
            return None
        rep = getattr(self.monitor, "last_report", None) \
            if self.monitor is not None else None
        if rep and rep.get("breaches", 0) > 0:
            return "slo_burn"
        mrep = getattr(self.memory_watch, "last_report", None) \
            if self.memory_watch is not None else None
        if mrep and mrep.get("pressure"):
            return "hbm_pressure"
        return None

    def _cull_queue(self):
        """Queued-side lifecycle pass before admission: drop requests
        whose deadline already passed (structured terminal record, not
        a wasted admission) and — under pressure — shed the lowest
        sheddable priority class."""
        if not self.queue:
            return
        now = time.monotonic()
        tr = _tracing.get_tracer()
        for req in [r for r in self.queue
                    if self._deadline_passed(r, now)]:
            self.queue.remove(req)
            _metrics.serve_deadline_exceeded().inc()
            # a preempted request can expire while re-queued: it still
            # carries the tokens it generated before eviction
            tr.event("deadline_exceeded", request=req.request_id,
                     status="deadline_exceeded",
                     generated=len(req.generated),
                     deadline_steps=req.deadline_steps)
            self._terminal_queued(req, "deadline_exceeded", "queued")
        reason = self._admission_pressure()
        if reason is None:
            return
        sheddable = [r for r in self.queue
                     if r.priority >= self.shed_priority_min]
        if not sheddable:
            return
        # one class per admission pass: shedding is a relief valve, not
        # a queue flush — the worst class goes first, the next only if
        # pressure persists into the next step
        worst = max(r.priority for r in sheddable)
        for req in [r for r in sheddable if r.priority == worst]:
            self.queue.remove(req)
            _metrics.serve_shed().labels(reason=reason).inc()
            tr.event("shed", request=req.request_id, status="shed",
                     reason=reason, priority=req.priority)
            self._terminal_queued(req, "shed", reason)

    def _pick_victim(self, below, exclude=None):
        """Preemption victim: the NEWEST-admitted active request of the
        strictly-lowest priority class below `below` (priority value
        strictly greater — equal classes never preempt each other, so
        two requests can't thrash swapping the same blocks). Returns
        the slot index or None."""
        best = None
        for j, r in enumerate(self.slots):
            if r is None or j == exclude or r.priority <= below:
                continue
            key = (r.priority, r._admit_seq or 0)
            if best is None or key > best[0]:
                best = (key, j)
        return None if best is None else best[1]

    def _preempt_slot(self, i, reason, q_lens=None, drafts=None):
        """Preempt slot i TO BLOCKS: free its KV pages (registered
        blocks park in the prefix reuse pool, so with the cache on its
        re-prefill is mostly a block-table copy), re-queue the request
        with its original arrival order (it sorts back to the front of
        its class), and cancel any work the current step had scheduled
        for it. The request keeps every token it generated; resumption
        re-prefills prompt + generated and decodes on, token-exact
        under greedy verification by construction."""
        req = self.slots[i]
        freed = len(req.blocks)
        self.allocator.free(req.blocks)
        req.blocks = []
        self.slots[i] = None
        self.tables[i] = 0
        self.lens[i] = 0
        self._dirty_slot(i)
        req.status = "preempted"
        req.preemptions += 1
        req.progress = 0
        req._cow_reserve = 0
        self.queue.append(req)
        if q_lens is not None:
            q_lens[i] = 0
        if drafts is not None:
            drafts.pop(i, None)
        self._sched_info.pop(i, None)
        _metrics.serve_preemptions().labels(reason=reason).inc()
        _tracing.get_tracer().event(
            "preempt", request=req.request_id, reason=reason,
            priority=req.priority, generated=len(req.generated),
            blocks_freed=freed)
        _tracing.get_flight_recorder().trigger(
            "preemption", request=req.request_id, preempt_reason=reason,
            step=self._step_count, priority=req.priority,
            blocks_freed=freed, generated=len(req.generated))
        self._update_pool_gauges()

    def _admit(self):
        # Priority admission with worst-case reservation: candidates in
        # (priority, arrival) order — all-default-priority traffic is
        # exactly the old FIFO — and a candidate is only admitted when
        # the pool covers its FULL footprint, so admitted requests
        # always finish. Matched shared blocks count as held
        # (len(r.blocks)), a mapped shared tail block keeps one COW
        # block reserved on top, and the pool side is num_available
        # because alloc() reclaims the LRU reuse pool before failing.
        # A blocked candidate first tries to preempt strictly-lower-
        # priority victims; if still blocked it blocks the line (no
        # lower-priority request may slip past and starve it).
        self._cull_queue()
        if not self.queue:
            return
        reserved = sum(
            r.blocks_needed(self.block_size) - len(r.blocks)
            + r._cow_reserve
            for r in self.slots if r is not None)
        for req in sorted(self.queue,
                          key=lambda r: (r.priority, r._seq or 0)):
            need = req.blocks_needed(self.block_size)
            slot_free = any(s is None for s in self.slots)
            # feasibility FIRST: preempting victim v raises admission
            # slack by exactly v.blocks_needed + v._cow_reserve (its
            # outstanding reservation returns AND its held blocks free)
            # — if even evicting every strictly-lower-priority victim
            # cannot cover the candidate, preempt NOBODY: destroying
            # in-flight work to still end up blocked buys nothing
            victims_gain = sum(
                r.blocks_needed(self.block_size) + r._cow_reserve
                for r in self.slots
                if r is not None and r.priority > req.priority)
            if reserved + need > self.allocator.num_available \
                    + victims_gain:
                # KV starvation no preemption can fix: the candidate is
                # blocked on pool capacity — the queue-wait outlier the
                # flight recorder's timeline should explain
                _tracing.get_tracer().event(
                    "admit_blocked", request=req.request_id,
                    blocks_needed=need, blocks_reserved=reserved,
                    blocks_free=self.allocator.num_free,
                    blocks_available=self.allocator.num_available)
                break
            if not slot_free:
                # every slot busy: a strictly-lower-priority victim
                # yields its SLOT (and its blocks) to the candidate —
                # otherwise a full batch of background work would
                # head-of-line-block front-door traffic forever
                victim = self._pick_victim(below=req.priority)
                if victim is None:
                    break
                vr = self.slots[victim]
                reserved -= (vr.blocks_needed(self.block_size)
                             - len(vr.blocks) + vr._cow_reserve)
                self._preempt_slot(victim, "admission")
            while reserved + need > self.allocator.num_available:
                # feasible by the check above: evict newest-lowest
                # until the candidate fits
                victim = self._pick_victim(below=req.priority)
                if victim is None:
                    break
                vr = self.slots[victim]
                reserved -= (vr.blocks_needed(self.block_size)
                             - len(vr.blocks) + vr._cow_reserve)
                self._preempt_slot(victim, "admission")
            if reserved + need > self.allocator.num_available:
                _tracing.get_tracer().event(
                    "admit_blocked", request=req.request_id,
                    blocks_needed=need, blocks_reserved=reserved,
                    blocks_free=self.allocator.num_free,
                    blocks_available=self.allocator.num_available)
                break
            i = min(i for i in range(self.max_batch)
                    if self.slots[i] is None)
            self.queue.remove(req)
            reserved += need
            req.blocks = []
            req.progress = 0
            req.cached_prefix = 0
            req._prefix_key = None
            req._registered = 0
            # resumption source: a fresh request prefills its prompt; a
            # preempted one re-prefills prompt + everything it already
            # emitted (the KV it gave back), then decode continues from
            # the exact token it was preempted at
            req._prefill_src = req.prompt if not req.generated \
                else req.prompt + [int(t) for t in req.generated]
            req._resume_len = len(req._prefill_src)
            if self._prefix_on:
                # the chained key ladder is a pure function of the
                # prefill source: hash it ONCE here so the per-step
                # scheduler dedup and wavefront probes index into it
                # instead of rehashing up to a chunk of tokens per slot
                # per step
                req._prompt_keys = prompt_block_keys(
                    req._prefill_src, self.block_size)
            req._miss_frontier = -1
            req._cow_reserve = 0
            req.status = "running"
            req._admit_seq = self._admit_counter
            self._admit_counter += 1
            req.admit_time = time.monotonic()
            if req.submit_time is not None:
                _metrics.serve_queue_wait().observe(
                    req.admit_time - req.submit_time)
            adm_pc = time.perf_counter()
            start_pc = req._submit_pc if req._submit_pc is not None \
                else adm_pc
            _tracing.get_tracer().record_span(
                "queue_wait", start_pc * 1e6, (adm_pc - start_pc) * 1e6,
                request=req.request_id, blocks_reserved=need)
            if req.preemptions:
                _tracing.get_tracer().event(
                    "resume", request=req.request_id,
                    generated=len(req.generated),
                    preemptions=req.preemptions)
            self.slots[i] = req
            self.tables[i] = 0
            self.lens[i] = 0
            self._dirty_slot(i)

    # -- automatic prefix caching -------------------------------------------

    def _extend_match(self, i):
        """Map full prompt blocks already in the prefix index straight
        into slot i's block table: those tokens' KV exists on some
        shared physical block, so the scheduler never grants them a
        prefill chunk. Runs at admission AND every step while the slot
        is block-aligned mid-prefill — the wavefront case: a follower
        whose prefix a leader is computing one chunk ahead maps each
        block the step after the leader registers it, paying zero model
        passes for the whole shared prefix.

        When the ENTIRE prompt is covered by index hits, the last token
        is handed back to the prefill scheduler anyway (its forward pass
        produces the first output token's logits); that one-token write
        lands INSIDE the shared tail block, which is exactly the
        copy-on-write trigger `_cow_block` resolves before the step
        writes. Returns the number of tokens newly mapped."""
        req = self.slots[i]
        bs = self.block_size
        src = req._prefill_src
        mapped = 0
        while True:
            p = req.progress
            if p % bs != 0 or p + bs > len(src):
                break
            key = req._prompt_keys[p // bs]
            blk = self.allocator.acquire(key)
            if blk is None:
                if p > req._miss_frontier:
                    # one miss per prompt position per request: the
                    # wavefront re-probes the same position every step
                    # until the leader registers it, which is not N
                    # misses
                    req._miss_frontier = p
                    self.cache_stats["miss_blocks"] += 1
                    _metrics.prefix_cache_misses().inc()
                break
            idx = len(req.blocks)
            req.blocks.append(blk)
            self.tables[i, idx] = blk
            self._dirty_slot(i)
            req._prefix_key = key
            req._registered += 1
            req.progress += bs
            self.lens[i] += bs
            mapped += bs
            self.cache_stats["hit_blocks"] += 1
            _metrics.prefix_cache_hits().inc()
        if mapped:
            if req.progress == req._resume_len:
                # whole prefill source cached: leave the LAST token to
                # the scheduler — sampling the next output token needs
                # its forward pass. progress stays mid-block, so the
                # write goes through COW on the shared tail block.
                req.progress -= 1
                self.lens[i] -= 1
                mapped -= 1
                req._cow_reserve = 1
            req.cached_prefix += mapped
            _tracing.get_tracer().event(
                "cache_hit", request=req.request_id, tokens=mapped,
                total=req.cached_prefix)
        return mapped

    def _cow_block(self, i, idx):
        """Copy-on-write: slot i must append into block-table entry
        `idx` but other holders still read the physical block there —
        duplicate it (one jitted all-layer copy, keyed once ever) and
        retarget the slot at the private copy. The old block keeps its
        index registration and remaining holders; the copy is
        unregistered (its content is about to diverge)."""
        req = self.slots[i]
        old = req.blocks[idx]
        try:
            new = self.allocator.alloc()
        except KVAllocFailure:
            # admission reserved the COW footprint (_cow_reserve), so
            # this alloc cannot fail — if it does (a reservation bug,
            # an injected fault), leave the COW-specific evidence on
            # the timeline and re-raise to the step's grow guard, which
            # preempts a lower-priority victim or (with no victim)
            # demotes this to a per-request failure with a dump
            _tracing.get_tracer().event(
                "stall_alloc", request=req.request_id,
                blocks_held=len(req.blocks),
                blocks_free=self.allocator.num_free,
                cow_block_index=idx)
            raise
        self.caches = self.engine._paged_copy(
            self.caches, np.int32(old), np.int32(new))
        self.allocator.free([old])      # decref; other holders keep it
        req.blocks[idx] = new
        self.tables[i, idx] = new
        self._dirty_slot(i)
        req._cow_reserve = 0
        self.cache_stats["cow_copies"] += 1
        _metrics.prefix_cache_cow().inc()
        _tracing.get_tracer().event(
            "cow_copy", request=req.request_id, block_index=idx,
            src_block=old, dst_block=new)
        return new

    def _register_full_blocks(self, i):
        """Publish slot i's newly FULL blocks into the prefix index.
        Runs after the step's accept/rewind settled lens, so every
        registered block is immutable: its tokens are committed prompt
        or committed generations (a rejected speculative span can never
        have been registered). Generated tokens register too — that is
        the conversation-resume path: a follow-up request whose prompt
        embeds this reply maps these blocks straight from the index."""
        req = self.slots[i]
        bs = self.block_size
        full = int(self.lens[i]) // bs
        if full <= req._registered:
            return
        # token at position p is seq[p]: the prompt, then every
        # generated token except the newest (which has not been fed —
        # and so not appended — yet); lens never covers it
        seq = req.prompt + req.generated
        key = req._prefix_key
        for k in range(req._registered, full):
            key = block_key(key, seq[k * bs:(k + 1) * bs])
            self.allocator.register(req.blocks[k], key)
        req._prefix_key = key
        req._registered = full

    def _schedule_tokens(self, active):
        """Fill this step's token budget: decode-phase slots are
        MANDATORY (one token each — a decode can't be deferred without
        stalling its request and holding its blocks hostage), then the
        remaining budget is spent on prompt chunks of up to
        `prefill_chunk` tokens, slot order, and ONLY THEN — budget
        permitting — decode slots are topped up with speculative draft
        spans (up to `spec_k` prompt-lookup tokens each, capped so a
        fully-accepted span can never overshoot max_new_tokens — which
        also keeps the step inside the admission reservation's
        worst-case block footprint). Drafts being last keeps the
        bucketed (work-list length, chunk-width) compile keys warm:
        speculation never displaces mandatory work, it only fills slack.
        A prefill slot the budget can't reach gets 0 tokens and simply
        stalls this step (it costs zero work-list entries).

        Returns (q_lens [max_batch] int64, drafts {slot: token list})."""
        q_lens = np.zeros(self.max_batch, np.int64)
        drafts = {}
        used = 0
        decode_slots = []
        for i in active:
            req = self.slots[i]
            if req.progress >= req._resume_len:
                q_lens[i] = 1
                used += 1
                decode_slots.append(i)
        budget = self.token_budget
        self._sched_info = {}   # prefill slot -> (requested, granted)
        self._pending_stalls = set()
        pending = set()     # block keys being computed by a slot THIS step
        for i in active:
            req = self.slots[i]
            rem = req._resume_len - req.progress
            if rem <= 0:
                continue
            keys = []
            if self._prefix_on:
                # concurrent-duplicate dedup: the full blocks this
                # slot's chunk would complete, by content key. If an
                # earlier slot is already computing this slot's NEXT
                # block this very step, defer — next step's wavefront
                # match maps it for free instead of computing it twice.
                p = req.progress
                if p % self.block_size == 0:
                    lo = p // self.block_size
                    n_full = min(self.prefill_chunk, rem) \
                        // self.block_size
                    keys = req._prompt_keys[lo:lo + n_full]
                if keys and keys[0] in pending:
                    self._pending_stalls.add(i)
                    continue
            room = rem if budget is None else min(rem, max(0, budget - used))
            take = min(self.prefill_chunk, room)
            if keys and take:
                # publish only the blocks THIS grant completes: a
                # budget-truncated (or zero) chunk must not claim keys
                # it will not compute, or a follower would defer on a
                # block nobody fills this step (a budget stall would be
                # misreported as cache-pending dedup)
                pending.update(keys[:take // self.block_size])
            q_lens[i] = take
            used += take
            # requested = what an unthrottled budget would have granted;
            # the delta IS budget starvation, span-visible per chunk
            self._sched_info[i] = (min(self.prefill_chunk, rem), take)
        if self.spec_k:
            for i in decode_slots:
                req = self.slots[i]
                # per-request spec cap: a request may ask for SHORTER
                # draft spans than the engine's spec_k (submit()
                # rejected anything wider)
                k_cap = self.spec_k if req.spec_k is None \
                    else min(req.spec_k, self.spec_k)
                if k_cap <= 0:
                    continue
                # a span of 1+k emits at most k+1 tokens: cap k at
                # rem_gen-1 so acceptance can never exceed the request
                rem_gen = req.max_new_tokens - len(req.generated)
                room = rem_gen - 1 if budget is None \
                    else min(rem_gen - 1, budget - used)
                if room <= 0:
                    continue
                d = propose_draft_tokens(req.prompt + req.generated,
                                         min(k_cap, room),
                                         self.spec_ngram)
                if d:
                    drafts[i] = d
                    q_lens[i] += len(d)
                    used += len(d)
        return q_lens, drafts

    def _fail_slot(self, i, reason, q_lens, drafts):
        """Demote an unsatisfiable allocation from an engine crash to a
        per-request failure: dump the timeline (the kv_alloc_failure
        flight trigger — same evidence the old re-raise left, minus the
        dead process), record the structured terminal status, and hand
        the slot's blocks back. Only reached when no preemptible victim
        exists."""
        req = self.slots[i]
        tr = _tracing.get_tracer()
        tr.event("stall_alloc", request=req.request_id,
                 blocks_held=len(req.blocks),
                 blocks_free=self.allocator.num_free,
                 tokens_wanted=int(q_lens[i]))
        tr.event("request_failed", request=req.request_id,
                 status="failed", reason=reason)
        _tracing.get_flight_recorder().trigger(
            "kv_alloc_failure", request=req.request_id,
            step=self._step_count, blocks_free=self.allocator.num_free)
        _metrics.serve_failed().labels(reason=reason).inc()
        self._finish_slot(i, "failed", reason)
        q_lens[i] = 0
        drafts.pop(i, None)
        self._sched_info.pop(i, None)
        self._update_pool_gauges()

    def _grow_slot(self, i, q_lens, drafts):
        """COW + block-grow for the span slot i computes this step.
        Admission reserved the worst-case footprint, so the allocs here
        cannot fail in normal flow; when one DOES (a reservation bug,
        an injected fault), the scheduler preempts the newest strictly-
        lower-priority victim to blocks and retries — the step loses
        the victim's work this tick, nobody crashes — and only with no
        victim left does the request itself fail (per-request, with a
        kv_alloc_failure dump)."""
        while self.slots[i] is not None:
            req = self.slots[i]
            try:
                end = int(self.lens[i] + q_lens[i])
                if self._prefix_on and q_lens[i]:
                    # copy-on-write BEFORE the step writes: any
                    # existing block this step's span appends into that
                    # other holders still read gets a private copy (the
                    # whole-prompt-cached tail block is the natural
                    # case)
                    lo = int(self.lens[i]) // self.block_size
                    hi = (end - 1) // self.block_size
                    for idx in range(lo, min(hi + 1, len(req.blocks))):
                        if self.allocator.refcount(req.blocks[idx]) > 1:
                            self._cow_block(i, idx)
                    # the first write settled every sharing conflict
                    # this request can ever have (it only appends at
                    # its tail): release the admission-side COW
                    # reservation even when the other holder retired
                    # first and no copy was needed
                    req._cow_reserve = 0
                while len(req.blocks) * self.block_size < end:
                    blk = self.allocator.alloc()
                    req.blocks.append(blk)
                    self.tables[i, len(req.blocks) - 1] = blk
                    self._dirty_slot(i)
                return
            except KVAllocFailure:
                # the allocator's exhaustion type ONLY: a device-side
                # RuntimeError out of the COW copy dispatch must
                # propagate, not be demoted to a per-request failure
                victim = self._pick_victim(below=req.priority, exclude=i)
                if victim is None:
                    self._fail_slot(i, "kv_alloc_failure", q_lens,
                                    drafts)
                    return
                self._preempt_slot(victim, "kv_alloc", q_lens=q_lens,
                                   drafts=drafts)

    def step(self):
        """One scheduler tick + one compiled mixed prefill/decode step.
        Returns the number of requests still in flight (active +
        queued)."""
        import jax

        t_begin = time.monotonic()
        pc_begin = time.perf_counter()
        tr = _tracing.get_tracer()
        self._retire()
        self._admit()
        active = [i for i, r in enumerate(self.slots) if r is not None]
        self._update_pool_gauges()
        if not active:
            if self.monitor is not None:
                self.monitor.tick()     # keep sampling through idle ticks
            if self.memory_watch is not None:
                self.memory_watch.tick()
            return len(self.queue)
        if self._prefix_on:
            # admission + wavefront prefix matching: map every full
            # prompt block the index already holds before the scheduler
            # spends budget on it (a just-admitted slot matches its
            # whole resident prefix; a mid-prefill follower picks up
            # the block its leader registered last step)
            for i in active:
                req = self.slots[i]
                if req.progress < req._resume_len:
                    self._extend_match(i)
        q_lens, drafts = self._schedule_tokens(active)
        for i in active:
            self._grow_slot(i, q_lens, drafts)
        # preemption/failure may have vacated slots mid-grow: the rest
        # of the step only sees the survivors (their q_lens are zeroed,
        # their table rows parked)
        active = [i for i in active if self.slots[i] is not None]
        if not active:
            if self.monitor is not None:
                self.monitor.tick()
            if self.memory_watch is not None:
                self.memory_watch.tick()
            return len(self.queue) + self.num_active
        pc_sched = time.perf_counter()
        # token slab [B, C]: C is the widest span this step, bucketed to
        # a power of two (1 for an all-decode step) so slab shapes — and
        # the programs they key — stay off the per-prompt-length
        # treadmill. Idle slots and budget-starved prefill slots have
        # q_len 0: zero slab tokens, zero work entries, output ignored.
        # Fast path: per-width persistent buffers zero-filled in place —
        # a steady-state step allocates nothing (a fresh width keys a
        # fresh compile anyway, so buffer creation rides warmup).
        c = int(next_pow2(int(q_lens.max())))
        if self._host_fastpath:
            slab = self._slab_bufs.get(c)
            if slab is None:
                slab = np.zeros((self.max_batch, c), np.int32)
                self._slab_bufs[c] = slab
            else:
                slab.fill(0)
        else:
            slab = np.zeros((self.max_batch, c), np.int32)
            self._count_input_bytes(slab.nbytes)
        for i in active:
            req = self.slots[i]
            n = int(q_lens[i])
            if req.progress < req._resume_len:
                slab[i, :n] = \
                    req._prefill_src[req.progress:req.progress + n]
            elif n:
                # decode: last real token, then the speculative drafts
                # (if granted) — the step verifies the whole span
                slab[i, 0] = req.generated[-1]
                d = drafts.get(i)
                if d:
                    slab[i, 1:1 + len(d)] = d
        # sample-position gather [B, W]: the device projects/samples only
        # these slab columns, so lm_head cost is bounded by 1 + spec_k
        # per slot, not the chunk width. Prefill slots read one column
        # (the chunk-final position), decode slots their whole 1+K span;
        # padding repeats column 0 (computed, ignored). W is a pure
        # function of c and the engine-static spec_k, so the (t_total,
        # c) bucket pair still keys every compile.
        w_sel = min(c, 1 + self.spec_k)
        if self._host_fastpath:
            sel = self._sel_bufs.get(w_sel)
            if sel is None:
                sel = np.zeros((self.max_batch, w_sel), np.int32)
                self._sel_bufs[w_sel] = sel
            else:
                sel.fill(0)
        else:
            sel = np.zeros((self.max_batch, w_sel), np.int32)
            self._count_input_bytes(sel.nbytes)
        for i in active:
            req = self.slots[i]
            n = int(q_lens[i])
            if n == 0:
                continue
            if req.progress < req._resume_len:
                sel[i, 0] = n - 1
            else:
                sel[i, :n] = np.arange(n)
        if self._host_fastpath:
            # in-place step inputs: the persistent int32 views mutate
            # under np.copyto/np.add, and the work list assembles
            # incrementally — only slots the dirty schedule touched
            # rebuild their segments (RaggedWorkBuilder)
            q_arr = self._q_arr_buf
            q_arr[:] = q_lens
            attn_lens = self._attn_buf
            np.add(self.lens, q_arr, out=attn_lens)
            work, _, t_total, pack = self._work_builder.build(
                self.tables, attn_lens, q_arr)
            if self._host_debug:
                self._check_host_state(attn_lens, q_arr, work, t_total,
                                       pack)
        else:
            q_arr = q_lens.astype(np.int32)
            attn_lens = (self.lens + q_arr).astype(np.int32)
            work, _, t_total, pack = build_ragged_work(
                self.tables, attn_lens, self.block_size, self._pack,
                bucket_to=next_pow2, q_lens=q_arr)
            self._count_input_bytes(q_arr.nbytes + attn_lens.nbytes
                                    + sum(a.nbytes for a in work))
        # the (padded work-list length, slab width) pair is the ONLY
        # shape the scheduler varies step to step — a pair not seen
        # before keys a fresh compile of the step program
        # (host-deterministic, so tests can assert this counter stays
        # flat after warmup)
        if (t_total, c) not in self._seen_buckets:
            self._seen_buckets.add((t_total, c))
            _metrics.serve_bucket_recompiles().labels(
                bucket=f"{t_total}x{c}").inc()
            tr.event("bucket_compile", bucket=f"{t_total}x{c}",
                     warm=self._warm)
            if self._warm:
                # post-warmup recompile: admission leaked a new shape
                # into the compiled-step keyspace — the silent
                # multi-second stall PR 3 made a counter, now a dump
                _tracing.get_flight_recorder().trigger(
                    "post_warmup_recompile", bucket=f"{t_total}x{c}",
                    step=self._step_count)
        self._key, sub = jax.random.split(self._key)
        comm_task = None
        if self._comm_tasks is not None:
            # the TP step's per-layer reduces, attributed through the
            # PR-9 collective path: payload bytes are pure aval math
            # (tp_step_comm_bytes — 2 psums/layer over the [B, C, E]
            # partial activations), the window is the dispatch-to-sync
            # span that CONTAINS the reduces, so the (psum, tp)
            # bandwidth gauge is a floor and collective_bytes_total
            # attributes the comms cost exactly
            comm_task = self._comm_tasks.start_task(
                "psum", group="tp",
                nbytes=self.engine.tp_step_comm_bytes(self.max_batch, c))
        pc_step = time.perf_counter()
        # tables/lens go in as the persistent scheduler arrays
        # themselves: jit snapshots committed numpy arguments at
        # dispatch, so host mutation AFTER this call (the overlap
        # window below, next step's bookkeeping) can never race the
        # device read — the per-step asarray round-trip the fast path
        # retired was pure copy discipline
        toks2, self.caches = self.engine._paged_step(
            self.engine._w, self.caches, slab, q_arr, sel,
            self.tables, self.lens, tuple(work),
            pack, np.float32(self._temp), np.float32(self._topp), sub)
        pc_disp = time.perf_counter()
        pc_ovl = pc_disp
        ticked = False
        emitted = 0
        rewinds = []    # (slot, new_end, old_end): rejected draft spans
        slot_spans = []  # (slot, request_id, span name, args) this step
        pre_done = set()    # slots the overlap window fully handled
        if self._overlap_fetch:
            # overlap window: host work that cannot depend on this
            # step's sampled tokens runs while the device executes —
            # starved-slot stall bookkeeping, prefill-chunk advancement
            # for chunks that do NOT complete their prompt (the prompt
            # is immutable; only the completing chunk samples a token),
            # and the monitor/memory tick cadence (which consequently
            # evaluates the PREVIOUS step's samples — the eager path
            # ticks after commit). Token-exact in every scheduler mode
            # (pinned by serve_bench --host): nothing here feeds the
            # accept/rewind loop.
            for i in active:
                req = self.slots[i]
                n = int(q_lens[i])
                if n == 0:
                    if req.progress < req._resume_len:
                        if i in self._pending_stalls:
                            tr.event("stall_cache_pending",
                                     request=req.request_id,
                                     prompt_remaining=req._resume_len
                                     - req.progress)
                        else:
                            tr.event("stall_budget",
                                     request=req.request_id,
                                     prompt_remaining=req._resume_len
                                     - req.progress,
                                     token_budget=self.token_budget)
                    pre_done.add(i)
                elif req.progress < req._resume_len \
                        and req.progress + n < req._resume_len:
                    requested, granted = self._sched_info.get(i, (n, n))
                    slot_spans.append(
                        (i, req.request_id, "prefill_chunk",
                         {"width": n, "granted": granted,
                          "requested": requested,
                          "progress": req.progress + n}))
                    self.lens[i] += n
                    req.progress += n
                    pre_done.add(i)
            if self.monitor is not None:
                self.monitor.tick()
            if self.memory_watch is not None:
                self.memory_watch.tick()
            ticked = True
            self._overlap_steps += 1
            pc_ovl = time.perf_counter()
        toks2 = np.asarray(toks2)      # [B, W]: a sample per sel column
        t_done = time.monotonic()
        pc_done = time.perf_counter()
        if comm_task is not None:
            # end AFTER the host read above synced the program: the
            # collective span covers real execution, not async enqueue
            self._comm_tasks.end_task(comm_task)
            comm_dur = comm_task.elapsed
            for i in active:
                if q_lens[i]:
                    rid = self.slots[i].request_id
                    self._comm_seconds[rid] = self._comm_seconds.get(
                        rid, 0.0) + comm_dur
        for i in active:
            if i in pre_done:
                continue        # settled in the overlap window above
            req = self.slots[i]
            n = int(q_lens[i])
            if n == 0:
                if req.progress < req._resume_len:
                    if i in self._pending_stalls:
                        # deferred on purpose: another slot is computing
                        # this slot's next block THIS step — next step's
                        # wavefront match maps it for free
                        tr.event("stall_cache_pending",
                                 request=req.request_id,
                                 prompt_remaining=req._resume_len
                                 - req.progress)
                    else:
                        # budget starvation: the prompt wanted a chunk
                        # and got zero work-list entries this step
                        tr.event("stall_budget", request=req.request_id,
                                 prompt_remaining=req._resume_len
                                 - req.progress,
                                 token_budget=self.token_budget)
                continue        # starved prefill slot: stalled this step
            if req.progress < req._resume_len:
                requested, granted = self._sched_info.get(i, (n, n))
                slot_spans.append((i, req.request_id, "prefill_chunk",
                                   {"width": n, "granted": granted,
                                    "requested": requested,
                                    "progress": req.progress + n}))
                self.lens[i] += n
                req.progress += n
                if req.progress == req._resume_len:
                    # the chunk ended the prompt: sel column 0 carried
                    # its last valid position — that sample is the
                    # request's FIRST output token
                    self._append_token(req, toks2[i, 0], t_done)
                    emitted += 1
            else:
                # decode: greedy-verify the drafted span (sel columns
                # 0..n-1 are slab positions 0..n-1). Column j's sample
                # is the model's choice after slab column j, so draft
                # d[a] (at slab column a+1) is accepted iff it EQUALS
                # sample a; the sample after the last accepted draft is
                # emitted too (it was computed against a fully-valid
                # prefix) — a+1 tokens out of one compiled step.
                d = drafts.get(i, [])
                k = len(d)               # n == 1 + k
                span = toks2[i, :n]
                a = 0
                while a < k and d[a] == int(span[a]):
                    a += 1
                self._append_span(req, span[:a + 1], t_done)
                emitted += a + 1
                slot_spans.append((i, req.request_id, "decode",
                                   {"emitted": a + 1, "drafted": k,
                                    "accepted": a}))
                old_end = int(self.lens[i]) + n
                new_end = int(self.lens[i]) + a + 1
                self.lens[i] = new_end
                if k:
                    req.spec_drafted += k
                    req.spec_accepted += a
                    _metrics.spec_draft_tokens().inc(k)
                    _metrics.spec_accepted_tokens().inc(a)
                    _metrics.spec_accept_len().observe(a)
                if new_end < old_end:
                    rewinds.append((i, new_end, old_end))
        blocks_freed = {}
        if rewinds:
            # device-side zeroing FIRST (it reads the table rows that
            # still point at the rejected positions), host block
            # rollback after; one jitted program covers every slot,
            # keyed by the same bucketed slab width as the step.
            #
            # Shared-block discipline: a rewound position inside a
            # block other requests still read must be COPIED, never
            # zeroed — a retained shared block gets a private COW copy
            # (the copy absorbs the zeroing), and a shared block the
            # rollback drops from this slot's table is merely
            # deref'd: its zero-write is retargeted at the reserved
            # parking block. The engine's append discipline makes both
            # cases unreachable in normal flow (drafts only ever land
            # in exclusively-held blocks), but the rewind must stay
            # safe against ANY sharing topology.
            ztab = self.tables
            if self._prefix_on:
                shared_drops = []
                for i, ne, oe in rewinds:
                    req = self.slots[i]
                    keep = -(-ne // self.block_size) if ne > 0 else 0
                    lo = ne // self.block_size
                    hi = (oe - 1) // self.block_size
                    for idx in range(lo, min(hi + 1, len(req.blocks))):
                        if self.allocator.refcount(req.blocks[idx]) > 1:
                            if idx < keep:
                                self._cow_block(i, idx)
                            else:
                                shared_drops.append((i, idx))
                if shared_drops:
                    if self._host_fastpath:
                        # persistent retarget scratch (lazy: only
                        # prefix-on rewinds with shared drops ever
                        # need a diverging table view)
                        if self._ztab_buf is None:
                            self._ztab_buf = self.tables.copy()
                        else:
                            np.copyto(self._ztab_buf, self.tables)
                        ztab = self._ztab_buf
                    else:
                        ztab = self.tables.copy()
                        self._count_input_bytes(ztab.nbytes)
                    for i, idx in shared_drops:
                        ztab[i, idx] = 0
            if self._host_fastpath:
                # persistent-buffer discipline (GL109 family): new_l IS
                # the settled lens array — jit snapshots it at dispatch
                # — and old_l reuses one preallocated scratch row
                new_l = self.lens
                old_l = self._rw_old_buf
                np.copyto(old_l, self.lens)
            else:
                new_l = self.lens.copy()
                old_l = self.lens.copy()
                self._count_input_bytes(new_l.nbytes + old_l.nbytes)
            for i, _, oe in rewinds:
                old_l[i] = oe
            self.caches = self.engine._paged_rewind(
                self.caches, ztab, new_l, old_l, c)
            for i, ne, _ in rewinds:
                blocks_freed[i] = self._rewind_blocks(i, ne)
            self._update_pool_gauges()
        if self._prefix_on:
            # AFTER accept/rewind settled lens: every newly-full block
            # is immutable now, publish it for other requests to map
            for i in active:
                self._register_full_blocks(i)
        # per-request lanes: every slot's work this step as one span
        # over the compiled-step window (the chunk widths, spec
        # accounting, and rewind block frees ride as args) — recorded
        # AFTER the rewind so blocks_freed is known
        for i, rid, name, args in slot_spans:
            if blocks_freed.get(i):
                args["blocks_freed"] = blocks_freed[i]
            tr.record_span(name, pc_step * 1e6,
                           (pc_done - pc_step) * 1e6, request=rid, **args)
        # span BEFORE the increment: its step label must match the
        # step= the flight-recorder triggers above stamped, so a dump's
        # context cross-references the right serve_step on the timeline
        dur = t_done - t_begin
        tr.record_span("serve_step", pc_begin * 1e6,
                       (pc_done - pc_begin) * 1e6, step=self._step_count,
                       work=t_total, chunk=c, emitted=emitted,
                       host_sched_us=int((pc_sched - pc_begin) * 1e6),
                       host_build_us=int((pc_step - pc_sched) * 1e6),
                       host_dispatch_us=int((pc_disp - pc_step) * 1e6),
                       host_overlap_us=int((pc_ovl - pc_disp) * 1e6),
                       host_fetch_us=int((pc_done - pc_ovl) * 1e6))
        self._step_count += 1
        _metrics.serve_step_seconds().observe(dur)
        if emitted:
            _metrics.serve_tokens_total().inc(emitted)
            _metrics.serve_tokens_per_s().set(
                emitted / dur if dur > 0 else 0.0)
        # set even at 0 (a prefill-bound step emits nothing): a stale
        # nonzero reading would overstate throughput exactly when the
        # engine is prompt-bound
        _metrics.serve_effective_tokens_per_step().set(emitted)
        self._maybe_shrink_chunk()
        if not ticked:
            # host-side cadence hooks: registry sample + burn-rate pass
            # when the monitor's cadence elapsed, a monotonic compare
            # otherwise — AFTER the step's own metrics landed, so a
            # breach evaluation always sees this step's samples (the
            # overlap window already ticked, one step behind, when
            # overlap_fetch is on)
            if self.monitor is not None:
                self.monitor.tick()
            if self.memory_watch is not None:
                # same cadence contract: HBM/census + hbm_pressure
                self.memory_watch.tick()
        pc_end = time.perf_counter()
        phases = {"schedule": pc_sched - pc_begin,
                  "build": pc_step - pc_sched,
                  "dispatch": pc_disp - pc_step,
                  "overlap": pc_ovl - pc_disp,
                  "fetch": pc_done - pc_ovl,
                  "commit": pc_end - pc_done}
        self._last_host_phases = phases
        hp = _metrics.serve_host_phase_seconds()
        hp.labels(phase="schedule").observe(phases["schedule"])
        hp.labels(phase="build").observe(phases["build"])
        hp.labels(phase="dispatch").observe(phases["dispatch"])
        hp.labels(phase="overlap").observe(phases["overlap"])
        hp.labels(phase="fetch").observe(phases["fetch"])
        hp.labels(phase="commit").observe(phases["commit"])
        wb = self._work_builder
        if wb is not None:
            # registry mirror of the builder's monotonic counters: inc
            # by this step's delta so the process-wide families stay
            # exact sums across engines
            last = self._wb_last
            cur = (wb.segments_reused, wb.segments_rebuilt,
                   wb.assemblies_incremental, wb.assemblies_full)
            segs = _metrics.serve_work_segments()
            if cur[0] > last[0]:
                segs.labels(event="reused").inc(cur[0] - last[0])
            if cur[1] > last[1]:
                segs.labels(event="rebuilt").inc(cur[1] - last[1])
            asm = _metrics.serve_work_assemblies()
            if cur[2] > last[2]:
                asm.labels(mode="incremental").inc(cur[2] - last[2])
            if cur[3] > last[3]:
                asm.labels(mode="full").inc(cur[3] - last[3])
            self._wb_last = cur
        return len(self.queue) + self.num_active

    def _rewind_blocks(self, i, new_end):
        """Host half of the speculative rewind: shrink slot i's block
        list to cover `new_end` tokens, freeing (and zeroing out of the
        table) every block past that — the block-boundary case where a
        rejection hands cache capacity straight back to the pool. The
        device half (`truncate_paged_kv_cache`) already zeroed the
        rejected positions, so a freed-then-reallocated block carries no
        stale KV (a SHARED dropped block is the exception: its
        zero-write was retargeted at the parking block, because the
        remaining holders still read the content — freeing here just
        drops this slot's reference). Returns the number of blocks
        handed back."""
        req = self.slots[i]
        need = -(-new_end // self.block_size) if new_end > 0 else 0
        freed = 0
        while len(req.blocks) > need:
            blk = req.blocks.pop()
            self.tables[i, len(req.blocks)] = 0
            self._dirty_slot(i)
            self.allocator.free([blk])
            freed += 1
        return freed

    def _maybe_shrink_chunk(self):
        """Latency-SLO chunk controller: when the rolling mean of decode
        TPOT exceeds the SLO, shrink `prefill_chunk` one power-of-two
        bucket (256 -> 128 -> 64 -> ... -> min_prefill_chunk) — prefill
        chunks are the schedulable knob, decode-1 is mandatory. The
        window clears on every shrink so each decision sees only
        post-shrink samples (a cooldown, not a ratchet)."""
        if self.tpot_slo is None:
            return
        if len(self._tpot_window) < self.SLO_WINDOW:
            return
        mean = sum(self._tpot_window) / len(self._tpot_window)
        if mean > self.tpot_slo:
            # the breach itself is flight-recorder-worthy even when the
            # controller has no chunk left to give back
            _tracing.get_flight_recorder().trigger(
                "tpot_slo_breach", tpot_mean_s=mean, slo_s=self.tpot_slo,
                prefill_chunk=self.prefill_chunk)
            if self.prefill_chunk > self.min_prefill_chunk:
                self.prefill_chunk = max(self.min_prefill_chunk,
                                         self.prefill_chunk // 2)
                _metrics.serve_prefill_chunk().set(self.prefill_chunk)
            # clear on EVERY breach, not just shrinks: each decision
            # sees only fresh samples, and a sustained breach at
            # min_prefill_chunk re-triggers once per full window (plus
            # the recorder's per-reason cooldown) instead of every step
            # — spamming flight_trigger events would evict the very
            # request spans a dump exists to keep
            self._tpot_window.clear()

    def _append_token(self, req, tok, now):
        """Record one generated token + its latency sample: the first
        token of a request closes its TTFT window (submit -> token),
        every later one is a time-per-output-token interval."""
        req.generated.append(int(tok))
        if req.first_token_time is None:
            req.first_token_time = now
            if req.submit_time is not None:
                _metrics.serve_ttft().observe(now - req.submit_time)
                _tracing.get_tracer().event(
                    "first_token", request=req.request_id,
                    ttft_s=now - req.submit_time)
        elif req._last_token_time is not None:
            _metrics.serve_tpot().observe(now - req._last_token_time)
        req._last_token_time = now
        if self.on_token is not None:
            self.on_token(req.request_id, [int(tok)], self._step_count)

    def _append_span(self, req, toks, now):
        """Record a verified decode span (the mandatory token + accepted
        drafts) with ONE latency interval: serve_tpot observes the
        span's effective per-token latency (interval / span length — a
        per-token loop would flood the histogram with zeros, every
        accepted draft landing at the same host timestamp), and the SLO
        controller window gets the FULL interval once, because the
        controller tracks step latency, which speculation does not
        shrink."""
        for t in toks:
            req.generated.append(int(t))
        if req.first_token_time is None:
            req.first_token_time = now
            if req.submit_time is not None:
                _metrics.serve_ttft().observe(now - req.submit_time)
                _tracing.get_tracer().event(
                    "first_token", request=req.request_id,
                    ttft_s=now - req.submit_time)
        elif req._last_token_time is not None:
            interval = now - req._last_token_time
            _metrics.serve_tpot().observe(interval / len(toks))
            self._tpot_window.append(interval)
        req._last_token_time = now
        if self.on_token is not None:
            self.on_token(req.request_id, [int(t) for t in toks],
                          self._step_count)

    def declare_warm(self):
        """Mark the compile-bucket warmup phase over: from here on, any
        FIRST SIGHTING of a (work-list length, chunk width) bucket is an
        anomaly — admission caused a recompile in steady state — and
        fires the flight recorder (`post_warmup_recompile`). Call after
        a representative warmup workload (the bench legs do) or once a
        production deployment has seen its traffic shapes."""
        self._warm = True

    def explain(self, request_id):
        """Per-request lifecycle digest from the span ring (TTFT, queue
        wait, chunk grants, stalls, spec accept rate) — the
        `request.explain()` view tools/request_trace.py renders from
        flight dumps, here served live. Spans are a bounded ring: a
        long-retired request may have aged out.

        Under tensor-parallel serving the digest additionally reports
        ``comm_s`` — the summed collective-bearing step windows this
        request was active in (the host-side attribution the per-step
        `collective` span records) — and the mesh width ``tp``."""
        out = _tracing.request_summary(request_id)
        # ISSUE 20: the engine's last-step host-phase split (seconds,
        # schedule/build/dispatch/overlap/fetch/commit) rides on every
        # digest — the live counterpart of the per-step `host` args the
        # serve_step spans carry into flight dumps
        out["host_phases"] = dict(self._last_host_phases)
        if self._tp > 1:
            out["tp"] = self._tp
            # live requests accumulate in the dict; terminal ones carry
            # their figure on the RequestResult (the dict entry is
            # popped at retirement so it cannot grow unboundedly)
            if request_id in self._comm_seconds:
                out["comm_s"] = self._comm_seconds[request_id]
            else:
                out["comm_s"] = getattr(
                    self.finished.get(request_id), "comm_s", 0.0)
        return out

    def run(self, max_steps=100000):
        """Drive step() until every submitted request has finished.
        Returns {request_id: generated token list}.

        step() already retires at the top of every tick, so the loop
        doesn't re-retire after each step; the one final _retire() flushes
        the requests the LAST step finished, so `finished` is complete
        when the queue drains."""
        steps = 0
        while self.queue or self.num_active:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError("continuous batching did not converge "
                                   f"within {max_steps} steps")
        self._retire()
        return dict(self.finished)
