"""Continuous-batching serving over the paged KV cache.

The vLLM-style serving loop the ROADMAP's "heavy traffic from millions of
users" regime needs: requests of wildly different lengths share one fixed
pool of cache blocks; a host-side free-list allocator hands blocks to
sequences as they grow and reclaims them the step a request finishes, and
every step runs ALL in-flight requests — some consuming whole CHUNKS of
their prompt (Sarathi-style chunked prefill under a per-step token
budget, so TTFT costs ceil(prompt/chunk) steps instead of prompt steps),
some mid-generation, some slots idle — as ONE compiled program
(FusedMultiTransformerEngine._paged_step over the ragged Pallas kernel,
ops/pallas/paged_attention.py).

Host/device split: the allocator, block tables, lengths, and scheduling
live on the host (tiny int arrays, zero device round trips beyond the
step itself); the device program's shape is keyed only by the bucketed
work-list length, so admission and retirement never trigger recompiles
past the first few power-of-two buckets.

Reference bar: vLLM's continuous batching scheduler + "Ragged Paged
Attention" (PAPERS.md); the reference framework's analogue is the
block_multihead_attention serving stack.
"""
import collections
import time

import numpy as np

from ...observability import instrument as _metrics
from ...ops.pallas.paged_attention import (build_ragged_work, default_pack,
                                           next_pow2)

__all__ = ["BlockAllocator", "GenerationRequest", "ContinuousBatchingEngine"]


class BlockAllocator:
    """Free-list over the paged KV cache's physical blocks.

    Block ids [reserved, num_blocks) are allocatable; ids below `reserved`
    are parking space (idle batch slots point their table row at block 0
    so the one compiled step program can write SOMEWHERE harmless)."""

    def __init__(self, num_blocks, reserved=1):
        if num_blocks <= reserved:
            raise ValueError(
                f"need more than {reserved} blocks (got {num_blocks})")
        self.num_blocks = num_blocks
        self.reserved = reserved
        self._free = list(range(num_blocks - 1, reserved - 1, -1))
        self._free_set = set(self._free)  # O(1) double-free check
        self.high_water = 0     # max blocks ever simultaneously in use

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return (self.num_blocks - self.reserved) - len(self._free)

    def alloc(self):
        if not self._free:
            _metrics.kv_alloc_failures().inc()
            raise RuntimeError("BlockAllocator: out of cache blocks")
        b = self._free.pop()
        self._free_set.discard(b)
        if self.num_used > self.high_water:
            self.high_water = self.num_used
        return b

    def free(self, blocks):
        for b in blocks:
            if not (self.reserved <= b < self.num_blocks):
                raise ValueError(f"freeing out-of-pool block {b}")
            if b in self._free_set:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)
            self._free_set.add(b)


class GenerationRequest:
    """One serving request: prompt ids in, up to max_new_tokens out."""

    _next_id = 0

    def __init__(self, prompt_ids, max_new_tokens, request_id=None):
        self.prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not self.prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.max_new_tokens = int(max_new_tokens)
        if request_id is None:
            request_id = GenerationRequest._next_id
            GenerationRequest._next_id += 1
        elif isinstance(request_id, int) and not isinstance(request_id, bool) \
                and request_id >= GenerationRequest._next_id:
            # a user-supplied int id RESERVES the auto counter past it, so
            # a later auto-assigned id can never silently collide with it
            GenerationRequest._next_id = request_id + 1
        self.request_id = request_id
        # runtime state (owned by the engine)
        self.blocks = []        # physical cache blocks, in table order
        self.progress = 0       # prompt tokens consumed so far
        self.generated = []
        # latency bookkeeping (host monotonic clock; set by the engine)
        self.submit_time = None
        self.admit_time = None
        self.first_token_time = None
        self._last_token_time = None

    @property
    def done(self):
        return len(self.generated) >= self.max_new_tokens

    def total_tokens(self):
        return len(self.prompt) + self.max_new_tokens

    def blocks_needed(self, block_size):
        return -(-self.total_tokens() // block_size)


class ContinuousBatchingEngine:
    """Per-step admission / retirement scheduler over a
    FusedMultiTransformerEngine's paged decode mode.

    Each step():
      1. retire finished requests (free their blocks — eviction),
      2. admit queued requests into idle slots (FIFO; a request is only
         admitted when the free list can cover its WORST-CASE footprint,
         so no in-flight request can ever starve mid-generation),
      3. fill the per-step TOKEN BUDGET (Sarathi-style chunked prefill):
         decode-phase slots are mandatory at one token each, then the
         remaining budget is spent on prompt CHUNKS of up to
         `prefill_chunk` tokens from prefill-phase slots in slot order —
         a 512-token prompt costs ceil(512/chunk) steps, not 512,
      4. grow each active sequence's block list to cover the tokens the
         step appends (a chunk may cross several block boundaries),
      5. run one compiled step over all slots: the whole mixed
         prefill+decode batch advances in ONE program over the ragged
         Pallas kernel, and each slot samples from its chunk's last
         valid position.

    Greedy sampling (temperature 0) by default; temperature/top_p thread
    straight through to the engine's fused sampler.

    `prefill_chunk=1` reproduces the PR-1 one-token-per-step prefill
    exactly; `token_budget=None` means unthrottled (every prefill slot
    gets a full chunk each step). Chunking is token-exact either way.
    """

    def __init__(self, engine, num_blocks, block_size, max_batch=8,
                 temperature=0.0, top_p=1.0, seed=0, prefill_chunk=64,
                 token_budget=None):
        import jax

        self.engine = engine
        self.block_size = int(block_size)
        self.max_batch = int(max_batch)
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.token_budget = None if token_budget is None \
            else int(token_budget)
        if self.token_budget is not None and self.token_budget < 1:
            raise ValueError("token_budget must be >= 1")
        self.max_blocks = engine.max_seq_len // self.block_size
        if self.max_blocks < 1:
            raise ValueError("block_size larger than engine.max_seq_len")
        self.allocator = BlockAllocator(num_blocks)
        self.caches = engine.new_paged_caches(num_blocks, self.block_size)
        self.tables = np.zeros((self.max_batch, self.max_blocks), np.int32)
        self.lens = np.zeros(self.max_batch, np.int32)
        self.slots = [None] * self.max_batch
        self.queue = collections.deque()
        self.finished = {}
        self._ids = set()       # queued + active ids: O(1) duplicate check
        self._temp = float(temperature)
        self._topp = float(top_p)
        self._key = jax.random.PRNGKey(int(seed))
        self._step_count = 0
        # padded work-list lengths already compiled for: the work list's
        # static length keys the decode program, so a length outside this
        # set means admission just caused an XLA recompile — the exact
        # event the "no recompiles past the first few buckets" contract
        # forbids in steady state. Counted per bucket so a test (and a
        # dashboard) can assert the counter stays flat.
        self._seen_buckets = set()
        kvh = self.caches[0].shape[1]
        num_q = engine.num_heads
        self._pack = default_pack(self.max_batch, num_q // kvh)

    # -- scheduling ---------------------------------------------------------

    def submit(self, request):
        # table capacity, NOT max_seq_len: when max_seq_len is not a
        # block multiple the table floor-divides down and the last
        # partial block's tokens are unreachable
        capacity = self.max_blocks * self.block_size
        if request.total_tokens() > capacity:
            raise ValueError(
                f"request {request.request_id}: {request.total_tokens()} "
                f"tokens exceeds the block-table capacity {capacity} "
                f"({self.max_blocks} blocks x {self.block_size})")
        if request.blocks_needed(self.block_size) > \
                self.allocator.num_blocks - self.allocator.reserved:
            raise ValueError(
                f"request {request.request_id} can never fit: needs "
                f"{request.blocks_needed(self.block_size)} blocks, pool "
                f"has {self.allocator.num_blocks - self.allocator.reserved}")
        rid = request.request_id
        # O(1): the live-id set tracks queued + active, `finished` keeps
        # the retired ones — no linear scan per submit
        if rid in self._ids or rid in self.finished:
            raise ValueError(f"duplicate request_id {rid}")
        request.submit_time = time.monotonic()
        self.queue.append(request)
        self._ids.add(rid)
        _metrics.serve_queue_depth().set(len(self.queue))

    @property
    def num_active(self):
        return sum(r is not None for r in self.slots)

    def _retire(self):
        retired = 0
        for i, req in enumerate(self.slots):
            if req is not None and req.done:
                self.allocator.free(req.blocks)
                req.blocks = []
                self.slots[i] = None
                self.tables[i] = 0
                self.lens[i] = 0
                self.finished[req.request_id] = list(req.generated)
                self._ids.discard(req.request_id)
                retired += 1
        if retired:
            _metrics.serve_requests_total().inc(retired)
            self._update_pool_gauges()

    def _update_pool_gauges(self):
        _metrics.kv_blocks_free().set(self.allocator.num_free)
        _metrics.kv_blocks_used().set(self.allocator.num_used)
        _metrics.kv_blocks_high_water().set(self.allocator.high_water)
        _metrics.serve_inflight().set(self.num_active)
        _metrics.serve_queue_depth().set(len(self.queue))

    def _admit(self):
        # FIFO with worst-case reservation: the head request waits until
        # its full footprint fits, so admitted requests always finish
        reserved = sum(
            r.blocks_needed(self.block_size) - len(r.blocks)
            for r in self.slots if r is not None)
        for i in range(self.max_batch):
            if not self.queue:
                break
            if self.slots[i] is not None:
                continue
            need = self.queue[0].blocks_needed(self.block_size)
            if reserved + need > self.allocator.num_free:
                break
            req = self.queue.popleft()
            reserved += need
            req.blocks = []
            req.progress = 0
            req.generated = []
            req.admit_time = time.monotonic()
            if req.submit_time is not None:
                _metrics.serve_queue_wait().observe(
                    req.admit_time - req.submit_time)
            self.slots[i] = req
            self.tables[i] = 0
            self.lens[i] = 0

    def _schedule_tokens(self, active):
        """Fill this step's token budget: decode-phase slots are
        MANDATORY (one token each — a decode can't be deferred without
        stalling its request and holding its blocks hostage), then the
        remaining budget is spent on prompt chunks of up to
        `prefill_chunk` tokens, slot order. A prefill slot the budget
        can't reach gets 0 tokens and simply stalls this step (it costs
        zero work-list entries). Returns q_lens [max_batch] int64."""
        q_lens = np.zeros(self.max_batch, np.int64)
        used = 0
        for i in active:
            req = self.slots[i]
            if req.progress >= len(req.prompt):
                q_lens[i] = 1
                used += 1
        budget = self.token_budget
        for i in active:
            req = self.slots[i]
            rem = len(req.prompt) - req.progress
            if rem <= 0:
                continue
            room = rem if budget is None else min(rem, max(0, budget - used))
            take = min(self.prefill_chunk, room)
            q_lens[i] = take
            used += take
        return q_lens

    def step(self):
        """One scheduler tick + one compiled mixed prefill/decode step.
        Returns the number of requests still in flight (active +
        queued)."""
        import jax

        t_begin = time.monotonic()
        self._retire()
        self._admit()
        active = [i for i, r in enumerate(self.slots) if r is not None]
        self._update_pool_gauges()
        if not active:
            return len(self.queue)
        q_lens = self._schedule_tokens(active)
        for i in active:
            # grow the block list to cover every token this step appends
            # (a prompt chunk may cross several block boundaries);
            # admission reserved the worst-case footprint, so alloc()
            # cannot fail here
            req = self.slots[i]
            end = int(self.lens[i] + q_lens[i])
            while len(req.blocks) * self.block_size < end:
                blk = self.allocator.alloc()
                req.blocks.append(blk)
                self.tables[i, len(req.blocks) - 1] = blk
        # token slab [B, C]: C is the widest span this step, bucketed to
        # a power of two (1 for an all-decode step) so slab shapes — and
        # the programs they key — stay off the per-prompt-length
        # treadmill. Idle slots and budget-starved prefill slots have
        # q_len 0: zero slab tokens, zero work entries, output ignored.
        c = int(next_pow2(int(q_lens.max())))
        slab = np.zeros((self.max_batch, c), np.int32)
        for i in active:
            req = self.slots[i]
            n = int(q_lens[i])
            if req.progress < len(req.prompt):
                slab[i, :n] = req.prompt[req.progress:req.progress + n]
            elif n:
                slab[i, 0] = req.generated[-1]
        q_arr = q_lens.astype(np.int32)
        attn_lens = (self.lens + q_arr).astype(np.int32)
        work, _, t_total, pack = build_ragged_work(
            self.tables, attn_lens, self.block_size, self._pack,
            bucket_to=next_pow2, q_lens=q_arr)
        # the (padded work-list length, slab width) pair is the ONLY
        # shape the scheduler varies step to step — a pair not seen
        # before keys a fresh compile of the step program
        # (host-deterministic, so tests can assert this counter stays
        # flat after warmup)
        if (t_total, c) not in self._seen_buckets:
            self._seen_buckets.add((t_total, c))
            _metrics.serve_bucket_recompiles().labels(
                bucket=f"{t_total}x{c}").inc()
        self._key, sub = jax.random.split(self._key)
        toks2, self.caches = self.engine._paged_step(
            self.engine._w, self.caches, slab, q_arr,
            np.asarray(self.tables), np.asarray(self.lens), tuple(work),
            pack, np.float32(self._temp), np.float32(self._topp), sub)
        toks2 = np.asarray(toks2)
        t_done = time.monotonic()
        emitted = 0
        for i in active:
            req = self.slots[i]
            n = int(q_lens[i])
            if n == 0:
                continue        # starved prefill slot: stalled this step
            self.lens[i] += n
            if req.progress < len(req.prompt):
                req.progress += n
                if req.progress == len(req.prompt):
                    # the chunk ended the prompt: the sample at its last
                    # valid position is the request's FIRST output token
                    self._append_token(req, toks2[i], t_done)
                    emitted += 1
            else:
                self._append_token(req, toks2[i], t_done)
                emitted += 1
        self._step_count += 1
        dur = t_done - t_begin
        _metrics.serve_step_seconds().observe(dur)
        if emitted:
            _metrics.serve_tokens_total().inc(emitted)
            _metrics.serve_tokens_per_s().set(
                emitted / dur if dur > 0 else 0.0)
        return len(self.queue) + self.num_active

    def _append_token(self, req, tok, now):
        """Record one generated token + its latency sample: the first
        token of a request closes its TTFT window (submit -> token),
        every later one is a time-per-output-token interval."""
        req.generated.append(int(tok))
        if req.first_token_time is None:
            req.first_token_time = now
            if req.submit_time is not None:
                _metrics.serve_ttft().observe(now - req.submit_time)
        elif req._last_token_time is not None:
            _metrics.serve_tpot().observe(now - req._last_token_time)
        req._last_token_time = now

    def run(self, max_steps=100000):
        """Drive step() until every submitted request has finished.
        Returns {request_id: generated token list}.

        step() already retires at the top of every tick, so the loop
        doesn't re-retire after each step; the one final _retire() flushes
        the requests the LAST step finished, so `finished` is complete
        when the queue drains."""
        steps = 0
        while self.queue or self.num_active:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError("continuous batching did not converge "
                                   f"within {max_steps} steps")
        self._retire()
        return dict(self.finished)
