"""Fused layer classes (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward,
FusedTransformerEncoderLayer, FusedBiasDropoutResidualLayerNorm). Thin
Layer wrappers over the functional fused tier (XLA fuses the graphs the
reference's megakernels fuse by hand)."""
import numpy as np

from ...nn.layer import Layer
from ...nn import initializer as I
from . import functional as F


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self._dropout_rate = dropout_rate
        self._attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        # reference qkv layout: [3, num_heads, head_dim, embed_dim]
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim],
            attr=qkv_weight_attr,
            default_initializer=I.XavierUniform())
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self._dropout_rate,
            attn_dropout_rate=self._attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._act = activation
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                  else act_dropout_rate)
        self._epsilon = epsilon
        self.normalize_before = normalize_before
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src, cache=None):
        return F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate, activation=self._act,
            ln1_epsilon=self._epsilon, ln2_epsilon=self._epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """Reference fused_transformer.py FusedTransformerEncoderLayer:
    fused MHA block + fused FFN block."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        if cache is not None:
            out, new_cache = out
            return self.ffn(out), new_cache
        return self.ffn(out)


class FusedBiasDropoutResidualLayerNorm(Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, epsilon=1e-5,
                 bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 name=None):
        super().__init__()
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.bias = self.create_parameter([embed_dim], attr=bias_attr,
                                          is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self._dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedLinear(Layer):
    """Linear through the gemm-epilogue path (reference
    incubate/nn/layer/fused_linear.py:83): bias-add fuses into the matmul
    (XLA does on TPU what cublasLt epilogues do on GPU)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)
        self.transpose_weight = transpose_weight

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              self.transpose_weight)


class FusedDropoutAdd(Layer):
    """dropout(x) + y in one region (reference
    incubate/nn/layer/fused_dropout_add.py; kernel
    fused_dropout_add_kernel.cu)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.fused_dropout_add(x, y, p=self.p, training=self.training,
                                   mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


def _layer_list(attrs, n, make):
    """Per-layer parameter list following the reference's attr-list
    convention (a list of attrs fixes num_layers)."""
    return [make(attrs[i] if isinstance(attrs, (list, tuple)) else attrs, i)
            for i in range(n)]


class FusedMultiTransformer(Layer):
    """Whole decoder stack as ONE op (reference
    incubate/nn/layer/fused_transformer.py:1071 over
    fused_multi_transformer_kernel.cu): n_layers × [LN → QKV(+rope) →
    cached attention → out-proj+residual → LN → FFN → residual], serving
    the same parameter layout; execution is the functional
    fused_multi_transformer (XLA-fused chain, GQA/int8/int4 variants in
    the serving engine)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, residual_alpha=1.0,
                 num_layers=-1, nranks=1, trans_qkvw=True, ring_id=-1,
                 name=None, norm_type="layernorm",
                 use_neox_rotary_style=False, gqa_group_size=-1):
        super().__init__()
        assert embed_dim > 0 and num_heads > 0 and dim_feedforward > 0
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) \
                if isinstance(qkv_weight_attrs, (list, tuple)) else 1
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._residual_alpha = residual_alpha
        self._trans_qkvw = trans_qkvw
        self._norm_type = norm_type
        self._use_neox_rotary_style = use_neox_rotary_style
        self._gqa_group_size = gqa_group_size
        self._dropout_rate = dropout_rate
        self.activation = activation
        self.num_layers = num_layers
        kv_heads = gqa_group_size if gqa_group_size > 0 else num_heads
        qkv_rows = num_heads + 2 * kv_heads

        def plist(name_, attrs, shape, init=None, bias=False):
            ps = _layer_list(
                attrs, num_layers,
                lambda a, i: self.create_parameter(
                    shape, attr=a, is_bias=bias,
                    default_initializer=init or I.XavierUniform()))
            for i, p_ in enumerate(ps):
                setattr(self, f"{name_}_{i}", p_)
            return ps

        hd = self.head_dim
        self.ln_scales = plist("ln_scale", ln_scale_attrs, [embed_dim],
                               I.Constant(1.0))
        self.ln_biases = plist("ln_bias", ln_bias_attrs, [embed_dim],
                               bias=True)
        # reference layout (trans_qkvw=True): [qkv_rows, head_dim, E];
        # split as [3, H, D, E] for MHA or GQA-packed rows
        self.qkv_weights = plist(
            "qkv_weight", qkv_weight_attrs,
            [3, num_heads, hd, embed_dim] if kv_heads == num_heads
            else [qkv_rows, hd, embed_dim])
        # bias layout matches the functional's [3, H, D] (MHA) /
        # [H + 2G, D] (GQA-packed) broadcast
        self.qkv_biases = plist(
            "qkv_bias", qkv_bias_attrs,
            [3, num_heads, hd] if kv_heads == num_heads
            else [qkv_rows, hd], bias=True)
        self.linear_weights = plist(
            "linear_weight", linear_weight_attrs,
            [num_heads * hd, embed_dim])
        self.linear_biases = plist("linear_bias", linear_bias_attrs,
                                   [embed_dim], bias=True)
        self.ffn_ln_scales = plist("ffn_ln_scale", ffn_ln_scale_attrs,
                                   [embed_dim], I.Constant(1.0))
        self.ffn_ln_biases = plist("ffn_ln_bias", ffn_ln_bias_attrs,
                                   [embed_dim], bias=True)
        ffn1_cols = dim_feedforward * (2 if "glu" in activation else 1)
        self.ffn1_weights = plist("ffn1_weight", ffn1_weight_attrs,
                                  [embed_dim, ffn1_cols])
        self.ffn1_biases = plist("ffn1_bias", ffn1_bias_attrs,
                                 [ffn1_cols], bias=True)
        self.ffn2_weights = plist("ffn2_weight", ffn2_weight_attrs,
                                  [dim_feedforward, embed_dim])
        self.ffn2_biases = plist("ffn2_bias", ffn2_bias_attrs,
                                 [embed_dim], bias=True)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        return F.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self._epsilon,
            residual_alpha=self._residual_alpha, cache_kvs=caches,
            pre_caches=pre_caches, seq_lens=seq_lens,
            rotary_embs=rotary_embs, time_step=time_step,
            attn_mask=attn_mask, dropout_rate=self._dropout_rate,
            rotary_emb_dims=rotary_emb_dims, activation=self.activation,
            training=self.training, trans_qkvw=self._trans_qkvw,
            norm_type=self._norm_type,
            use_neox_rotary_style=self._use_neox_rotary_style,
            gqa_group_size=self._gqa_group_size)
