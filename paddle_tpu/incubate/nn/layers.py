"""Fused layer classes (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward,
FusedTransformerEncoderLayer, FusedBiasDropoutResidualLayerNorm). Thin
Layer wrappers over the functional fused tier (XLA fuses the graphs the
reference's megakernels fuse by hand)."""
import numpy as np

from ...nn.layer import Layer
from ...nn import initializer as I
from . import functional as F


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self._dropout_rate = dropout_rate
        self._attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        # reference qkv layout: [3, num_heads, head_dim, embed_dim]
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim],
            attr=qkv_weight_attr,
            default_initializer=I.XavierUniform())
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self._dropout_rate,
            attn_dropout_rate=self._attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._act = activation
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                  else act_dropout_rate)
        self._epsilon = epsilon
        self.normalize_before = normalize_before
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src, cache=None):
        return F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate, activation=self._act,
            ln1_epsilon=self._epsilon, ln2_epsilon=self._epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """Reference fused_transformer.py FusedTransformerEncoderLayer:
    fused MHA block + fused FFN block."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        if cache is not None:
            out, new_cache = out
            return self.ffn(out), new_cache
        return self.ffn(out)


class FusedBiasDropoutResidualLayerNorm(Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, epsilon=1e-5,
                 bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 name=None):
        super().__init__()
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.bias = self.create_parameter([embed_dim], attr=bias_attr,
                                          is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self._dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)
