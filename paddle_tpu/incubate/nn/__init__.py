"""paddle.incubate.nn (reference: python/paddle/incubate/nn/ — fused layer
classes + functional bindings)."""
from . import functional  # noqa: F401
from .layers import (FusedMultiHeadAttention, FusedFeedForward,  # noqa: F401
                     FusedTransformerEncoderLayer,
                     FusedBiasDropoutResidualLayerNorm,
                     FusedLinear, FusedDropoutAdd, FusedMultiTransformer)
from .continuous_batching import (BlockAllocator,  # noqa: F401
                                  GenerationRequest, RequestResult,
                                  KVAllocFailure,
                                  ContinuousBatchingEngine,
                                  propose_draft_tokens,
                                  block_key, prompt_block_keys)
