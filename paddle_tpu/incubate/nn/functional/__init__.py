"""Fused-op python bindings (reference: python/paddle/incubate/nn/
functional/ — fused_multi_head_attention, fused_feedforward,
fused_rotary_position_embedding, masked_multihead_attention,
block_multihead_attention; kernels in paddle/phi/kernels/fusion/gpu/,
SURVEY.md §2.9).

On TPU the "fusion" is either a Pallas kernel (attention family) or a
jnp composition XLA fuses on its own (rope/bias_act/dropout_add — the MXU
epilogue fusions the reference hand-writes in CUDA)."""
import math

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op
from ....core import random as _random
from ....nn.functional.rope import fused_rotary_position_embedding  # noqa: F401

NEG_INF_F = -1e30

__all__ = [
    "fused_multi_head_attention", "fused_feedforward", "fused_bias_act",
    "fused_dropout_add", "fused_bias_dropout_residual_layer_norm",
    "fused_rotary_position_embedding", "masked_multihead_attention",
    "block_multihead_attention", "fused_linear_param_grad_add",
    "flashmask_attention", "fused_multi_transformer",
    "fused_multi_transformer_int8", "fused_multi_transformer_int4",
    "quantize_int4",
    "fused_matmul_bias", "fused_linear", "fused_linear_activation",
    "fused_moe", "variable_length_memory_efficient_attention",
    "fused_rms_norm", "fused_layer_norm", "blha_get_max_len", "swiglu",
    "block_kv_cache_rewind",
]


def _ln(h, eps, scale=None, bias=None):
    mu = h.mean(-1, keepdims=True)
    var = ((h - mu) ** 2).mean(-1, keepdims=True)
    out = (h - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, num_heads=None):
    """Reference fused_attention_kernel.cu semantics: [pre-LN] -> QKV proj
    -> MHA -> out proj -> residual add [-> post-LN]. One traced graph —
    XLA fuses what the CUDA megakernel fuses by hand."""
    def impl(xa, qkvw, lw, *rest):
        it = iter(rest)
        cache = next(it) if cache_kv is not None else None
        mask_arr = next(it) if attn_mask is not None else None
        plns = next(it) if pre_ln_scale is not None else None
        plnb = next(it) if pre_ln_bias is not None else None
        qb = next(it) if qkv_bias is not None else None
        lb = next(it) if linear_bias is not None else None
        lns = next(it) if ln_scale is not None else None
        lnb = next(it) if ln_bias is not None else None
        kit = it  # trailing args are the dropout keys

        h = _ln(xa, pre_ln_epsilon, plns, plnb) if pre_layer_norm else xa
        b, s, dm = h.shape
        # qkv_weight: [3, num_heads, head_dim, dim] (reference layout)
        nh, hd = qkvw.shape[1], qkvw.shape[2]
        qkv = jnp.einsum("bsd,tnhd->tbsnh", h, qkvw,
                         preferred_element_type=jnp.float32).astype(h.dtype)
        if qb is not None:
            qkv = qkv + qb.reshape(3, 1, 1, nh, hd)
        q, k, v = qkv[0], qkv[1], qkv[2]          # [B, S, H, hd]
        new_cache = None
        if cache is not None:
            # decode: attend over cached K/V ++ current chunk and return
            # the extended cache (reference CacheKV branch)
            k = jnp.concatenate([cache[0], k], axis=1)
            v = jnp.concatenate([cache[1], v], axis=1)
            new_cache = jnp.stack([k, v])
        scale = 1.0 / math.sqrt(hd)
        logits = jnp.einsum("bsnh,btnh->bnst", q, k,
                            preferred_element_type=jnp.float32) * scale
        if mask_arr is not None:
            logits = logits + mask_arr.astype(logits.dtype)
        p = jax.nn.softmax(logits, axis=-1)
        if training and attn_dropout_rate > 0.0:
            keep = jax.random.bernoulli(next(kit),
                                        1.0 - attn_dropout_rate, p.shape)
            p = jnp.where(keep, p / (1.0 - attn_dropout_rate), 0.0)
        ctx = jnp.einsum("bnst,btnh->bsnh", p,
                         v.astype(jnp.float32)).astype(h.dtype)
        out = jnp.einsum("bse,ed->bsd", ctx.reshape(b, s, nh * hd), lw)
        if lb is not None:
            out = out + lb
        if training and dropout_rate > 0.0:
            keep = jax.random.bernoulli(next(kit),
                                        1.0 - dropout_rate, out.shape)
            out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
        out = xa + out                             # residual
        if not pre_layer_norm:
            out = _ln(out, ln_epsilon, lns, lnb)
        return out if new_cache is None else (out, new_cache)

    # dropout keys ride as INPUT leaves (philox-as-data discipline,
    # core/random.py): the op stays vjp-cacheable and every capture tier
    # re-draws per call
    n_keys = int(training and attn_dropout_rate > 0.0) + \
        int(training and dropout_rate > 0.0)
    args = [x, qkv_weight, linear_weight]
    for t in (cache_kv, attn_mask, pre_ln_scale, pre_ln_bias, qkv_bias,
              linear_bias, ln_scale, ln_bias):
        if t is not None:
            args.append(t)
    args += [_random.fresh_key_tensor() for _ in range(n_keys)]
    return apply_op("fused_multi_head_attention", impl, tuple(args), {})


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True):
    """Reference fused_feedforward_kernel.cu: [pre-LN] -> FC1 -> act ->
    FC2 -> residual [-> post-LN]."""
    def impl(xa, w1, w2, *rest):
        it = iter(rest)
        b1 = next(it) if linear1_bias is not None else None
        b2 = next(it) if linear2_bias is not None else None
        s1 = next(it) if ln1_scale is not None else None
        sb1 = next(it) if ln1_bias is not None else None
        s2 = next(it) if ln2_scale is not None else None
        sb2 = next(it) if ln2_bias is not None else None

        kit = it  # trailing args are the dropout keys

        def _drop(t, rate):
            if not training or rate <= 0.0:
                return t
            keep = jax.random.bernoulli(next(kit), 1.0 - rate, t.shape)
            return jnp.where(keep, t / (1.0 - rate), 0.0)

        h = _ln(xa, ln1_epsilon, s1, sb1) if pre_layer_norm else xa
        h = jnp.einsum("...d,de->...e", h, w1)
        if b1 is not None:
            h = h + b1
        act = {"relu": jax.nn.relu,
               "gelu": lambda t: jax.nn.gelu(t, approximate=False),
               "silu": jax.nn.silu}[activation]
        h = _drop(act(h), dropout1_rate)
        h = jnp.einsum("...e,ed->...d", h, w2)
        if b2 is not None:
            h = h + b2
        out = xa + _drop(h, dropout2_rate)
        if not pre_layer_norm:
            out = _ln(out, ln2_epsilon, s2, sb2)
        return out

    args = [x, linear1_weight, linear2_weight]
    for t in (linear1_bias, linear2_bias, ln1_scale, ln1_bias, ln2_scale,
              ln2_bias):
        if t is not None:
            args.append(t)
    n_keys = int(training and dropout1_rate > 0.0) + \
        int(training and dropout2_rate > 0.0)
    args += [_random.fresh_key_tensor() for _ in range(n_keys)]
    return apply_op("fused_feedforward", impl, tuple(args), {})


def fused_bias_act(x, bias=None, act_method="gelu"):
    """Reference fused_bias_act_kernel.cu (plain and gated activations)."""
    def impl(xa, *rest):
        h = xa + rest[0] if rest else xa
        if act_method in ("geglu", "swiglu"):
            a, b = jnp.split(h, 2, axis=-1)
            base = jax.nn.gelu if act_method == "geglu" else jax.nn.silu
            return base(a) * b
        act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
               "silu": jax.nn.silu}[act_method]
        return act(h)

    args = (x,) if bias is None else (x, bias)
    return apply_op("fused_bias_act", impl, args, {})


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train"):
    """Reference fused_dropout_add_kernel.cu: dropout(x) + y."""
    def impl(xa, ya, *rk):
        if mode == "downscale_in_infer":
            # train: drop without rescale; infer: scale by (1-p)
            if not training:
                return xa * (1.0 - p) + ya
            if p == 0.0:
                return xa + ya
            keep = jax.random.bernoulli(rk[0], 1.0 - p, xa.shape)
            return jnp.where(keep, xa, 0.0) + ya
        if not training or p == 0.0:
            return xa + ya
        keep = jax.random.bernoulli(rk[0], 1.0 - p, xa.shape)
        return jnp.where(keep, xa / (1.0 - p), 0.0) + ya

    args = (x, y)
    if training and p > 0.0:
        args = args + (_random.fresh_key_tensor(),)
    return apply_op("fused_dropout_add", impl, args, {})


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5,
                                           training=True):
    """Reference fused_bias_dropout_residual_layer_norm_kernel.cu."""
    def impl(xa, res, *rest):
        it = iter(rest)
        b = next(it) if bias is not None else None
        s = next(it) if ln_scale is not None else None
        lb = next(it) if ln_bias is not None else None
        h = xa if b is None else xa + b
        if training and dropout_rate > 0.0:
            keep = jax.random.bernoulli(next(it),
                                        1.0 - dropout_rate, h.shape)
            h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
        return _ln(h + res, ln_epsilon, s, lb)

    args = [x, residual]
    for t in (bias, ln_scale, ln_bias):
        if t is not None:
            args.append(t)
    if training and dropout_rate > 0.0:
        args.append(_random.fresh_key_tensor())
    return apply_op("fused_bias_dropout_residual_layer_norm", impl,
                    tuple(args), {})


def fused_linear_param_grad_add(x, dout, dweight=None, dbias=None,
                                multi_precision=True, has_bias=True):
    """Reference fused_linear_param_grad_add_kernel.cu: dW += x^T·dout
    (and db += sum(dout)) fused into gradient accumulation — the building
    block sharding/auto-parallel use for param-grad accumulation."""
    def impl(xa, doa, *rest):
        it = iter(rest)
        dw = next(it) if dweight is not None else None
        db = next(it) if dbias is not None else None
        # accumulate in f32 always (MXU-native); emit f32 master grads
        # under multi_precision, else the incoming grad dtype
        out_t = jnp.float32 if multi_precision else doa.dtype
        dW = jnp.einsum("...i,...o->io", xa.astype(jnp.float32),
                        doa.astype(jnp.float32))
        if dw is not None:
            dW = dw.astype(jnp.float32) + dW
        outs = [dW.astype(out_t)]
        if has_bias:
            red = tuple(range(doa.ndim - 1))
            dB = doa.astype(jnp.float32).sum(axis=red)
            if db is not None:
                dB = db.astype(jnp.float32) + dB
            outs.append(dB.astype(out_t))
        return tuple(outs) if len(outs) > 1 else outs[0]

    args = [x, dout]
    for t in (dweight, dbias):
        if t is not None:
            args.append(t)
    return apply_op("fused_linear_param_grad_add", impl, tuple(args), {},
                    differentiable=False)


def masked_multihead_attention(x, cache_kv, seq_lens, src_mask=None,
                               **kwargs):
    """Decode-step MHA over a contiguous KV cache (reference
    masked_multihead_attention_kernel.cu). x: [B, 3*H*D] fused qkv of the
    new token; cache_kv: [2, B, H, S_max, D]; seq_lens: [B] current
    lengths; src_mask (optional): additive logits bias broadcastable to
    [B, H, S_max] (e.g. -inf at excluded slots, or ALiBi biases).
    Returns (out [B, H*D], updated cache_kv)."""
    def impl(xa, cache, lens, *rest):
        two, b, h, smax, d = cache.shape
        qkv = xa.reshape(b, 3, h, d)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        bidx = jnp.arange(b)
        kc = cache[0].at[bidx, :, lens].set(k_new)
        vc = cache[1].at[bidx, :, lens].set(v_new)
        scale = 1.0 / math.sqrt(d)
        s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                       kc.astype(jnp.float32)) * scale
        if rest:
            s = s + rest[0].reshape(b, -1, smax).astype(s.dtype)
        pos = jnp.arange(smax)[None, None, :]
        s = jnp.where(pos <= lens[:, None, None], s, NEG_INF_F)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", p,
                         vc.astype(jnp.float32)).astype(xa.dtype)
        return out.reshape(b, h * d), jnp.stack([kc, vc])

    args = (x, cache_kv, seq_lens)
    if src_mask is not None:
        args = args + (src_mask,)
    return apply_op("masked_multihead_attention", impl, args, {},
                    differentiable=False)


def block_multihead_attention(qkv, k_cache, v_cache, block_tables,
                              context_lens, scale=None):
    """Paged-cache decode attention (reference
    block_multi_head_attention_kernel.cu). qkv: [B, 3, H, D] for the new
    token; caches [KVH, num_blocks, block_size, D] (KVH == H or a divisor
    for GQA — the kv slice of qkv uses heads [0:KVH]). Appends the token,
    then attends via the Pallas paged kernel. Returns
    (out [B, H, D], k_cache, v_cache)."""
    from ....ops.pallas.paged_attention import (paged_attention,
                                               update_paged_kv_cache)

    def impl(qkv_a, kc, vc, tables, lens):
        kvh = kc.shape[0]
        q, k_new, v_new = qkv_a[:, 0], qkv_a[:, 1], qkv_a[:, 2]
        if q.shape[1] != kvh:
            k_new = k_new[:, :kvh]
            v_new = v_new[:, :kvh]
        kc, vc = update_paged_kv_cache(kc, vc, k_new, v_new, tables, lens)
        out = paged_attention(q, kc, vc, tables, lens + 1, scale=scale)
        return out, kc, vc

    return apply_op("block_multihead_attention", impl,
                    (qkv, k_cache, v_cache, block_tables, context_lens),
                    {}, differentiable=False)


def block_kv_cache_rewind(k_cache, v_cache, block_tables, new_lens,
                          old_lens, max_span):
    """Speculative-decode rewind over the paged KV cache: zero positions
    new_lens[b] .. old_lens[b]-1 (the KV a rejected draft span appended)
    so the cache is bit-identical to one that never speculated. Caches
    [KVH, num_blocks, block_size, D]; new_lens/old_lens [B] int32;
    `max_span` a static python int bounding the widest rewind. Returns
    (k_cache, v_cache). The serving engine batches all slots' rewinds
    into one call of this per step (FusedMultiTransformerEngine's
    `_paged_rewind` applies it to every layer in one jitted program)."""
    from ....ops.pallas.paged_attention import truncate_paged_kv_cache
    span = int(max_span)

    def impl(kc, vc, tables, nl, ol):
        return truncate_paged_kv_cache(kc, vc, tables, nl, ol, span)

    return apply_op("block_kv_cache_rewind", impl,
                    (k_cache, v_cache, block_tables, new_lens, old_lens),
                    {}, differentiable=False)


def flashmask_attention(query, key, value, startend_row_indices,
                        causal=True):
    """FlashMask sparse-interval attention (reference
    flash_attention.py:1299) — Pallas kernel on TPU (or interpret mode),
    dense-mask XLA fallback elsewhere. Layout [B, S, H, D]."""
    from ....ops.pallas import flash_attention as _fa
    from ....ops.pallas.flashmask import flashmask_attention_bshd

    on_tpu = jax.devices()[0].platform == "tpu" or _fa._INTERPRET

    def impl(q, k, v, idx):
        if on_tpu:
            return flashmask_attention_bshd(q, k, v, idx, causal=causal)
        # dense fallback: materialize the interval mask
        b, s, hq, d = q.shape
        if k.shape[2] != hq:  # GQA: broadcast kv heads like the kernel path
            rep = hq // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        sr = idx[..., 0]
        er = idx[..., 1] if idx.shape[-1] > 1 else jnp.full_like(sr, s)
        if sr.shape[1] != hq:
            sr = jnp.repeat(sr, hq // sr.shape[1], axis=1)
            er = jnp.repeat(er, hq // er.shape[1], axis=1)
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(s)[None, :]
        allowed = jnp.ones((s, s), bool) if not causal else rows >= cols
        allowed = allowed[None, None] & ~(
            (rows[None, None] >= sr[:, :, None, :])
            & (rows[None, None] < er[:, :, None, :]))
        logits = jnp.einsum("bshd,bthd->bhst", q, k,
                            preferred_element_type=jnp.float32) \
            / math.sqrt(d)
        logits = jnp.where(allowed, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        p = jnp.where(allowed.any(-1, keepdims=True), p, 0.0)
        return jnp.einsum("bhst,bthd->bshd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    return apply_op("flashmask_attention", impl,
                    (query, key, value, startend_row_indices), {})


def _rms(h, eps, scale=None):
    out = h * jax.lax.rsqrt((h * h).mean(-1, keepdims=True) + eps)
    return out * scale if scale is not None else out


def _apply_rope_pair(q, k, cos, sin, neox):
    """q/k: [B, S, H, D]; cos/sin broadcastable [B, S, 1, D]."""
    if neox:
        half = q.shape[-1] // 2

        def rot(t):
            return jnp.concatenate([-t[..., half:], t[..., :half]], axis=-1)
    else:
        def rot(t):
            t2 = t.reshape(*t.shape[:-1], -1, 2)
            r = jnp.stack([-t2[..., 1], t2[..., 0]], axis=-1)
            return r.reshape(t.shape)
    return q * cos + rot(q) * sin, k * cos + rot(k) * sin


def _ragged_group_q(qkv_weights, gqa_group_size, trans_qkvw):
    """Queries per kv head, recovered from the packed qkv weight layout
    (needed to pick the ragged kernel's default pack factor)."""
    w0 = qkv_weights[0]
    shape = (w0.data if hasattr(w0, "data") else w0).shape
    if gqa_group_size and gqa_group_size > 0:
        ht = shape[0] if trans_qkvw else shape[1]
        return (ht - 2 * gqa_group_size) // gqa_group_size
    return 1


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, residual_alpha=1.0, cache_kvs=None, beam_offset=None,
        pre_caches=None, seq_lens=None, rotary_embs=None, time_step=None,
        attn_mask=None, dropout_rate=0.0, rotary_emb_dims=0,
        activation="gelu", training=False, mode="upscale_in_train",
        trans_qkvw=True, ring_id=-1, norm_type="layernorm",
        use_neox_rotary_style=False, gqa_group_size=-1, name=None,
        block_tables=None, ragged_work=None, ragged_pack=None,
        chunk_lens=None, kv_buffer_depth=2, _dequant=None, _mm=None,
        _tp_reduce=None):
    """Whole-decoder-stack fused transformer (reference
    fused_multi_transformer op: python/paddle/incubate/nn/functional/
    fused_transformer.py:1053 over
    paddle/phi/kernels/fusion/gpu/fused_multi_transformer_kernel.cu).

    One call runs EVERY decoder layer: [LN → QKV proj (+rope) → cached
    attention → out proj + residual → LN → FFN → residual] × n_layers.
    On TPU the per-layer chain is a jnp composition XLA fuses into the
    matmuls (the epilogue fusions the CUDA kernel hand-writes); decode
    attention over the contiguous [2, B, H, S_max, D] cache is a masked
    einsum the TPU executes from VMEM. The paged-cache serving path is
    `block_multihead_attention` (Pallas decode kernel,
    ops/pallas/paged_attention.py).

    Shapes (trans_qkvw=True, the reference default):
    x [B, S, E]; qkv_weight [3, H, D, E]; linear_weight [H*D, E];
    ffn1_weight [E, F] (or [E, 2F] for *glu activations); ffn2 [F, E];
    cache_kvs: list of [2, B, H, S_max, D] per layer, updated in place;
    rotary_embs [2, B, 1, S_rope, D] (cos, sin); time_step: scalar int
    tensor = current decode position (decode mode when given).

    Paged-cache decode (the continuous-batching serving path): pass
    `block_tables` [B, max_blocks] plus per-layer caches shaped
    [2, KVH, num_blocks, block_size, D] and per-sequence `seq_lens`; the
    attention runs the ragged Pallas kernel
    (ops/pallas/paged_attention.ragged_paged_attention) after appending
    the new token at slot seq_lens. `ragged_work` is the host-built
    flattened work list (`build_ragged_work(tables, seq_lens + 1, ...)`
    — +1 because attention covers the token just appended); required
    under jit where seq_lens is traced. x is [B, 1, E] with time_step
    set (classic decode), or — CHUNKED PREFILL — [B, C, E] with
    `chunk_lens` [B] giving how many of each row's C token columns are
    valid this step: sequence b's chunk_lens[b] tokens append at
    positions seq_lens[b].. and each attends causally to its own prefix
    (the work list must then be built with
    `build_ragged_work(tables, seq_lens + chunk_lens, ...,
    q_lens=chunk_lens)`). chunk_lens[b] == 0 parks the row: nothing
    written, nothing attended, output rows zero.

    Returns the output hidden states [B, S, E]; caches are updated
    in place (dygraph reference semantics).
    """
    from ....core.tensor import Tensor

    if beam_offset is not None:
        raise NotImplementedError(
            "fused_multi_transformer: beam_offset unsupported")
    if chunk_lens is not None and block_tables is None:
        raise ValueError(
            "fused_multi_transformer: chunk_lens (chunked prefill) is a "
            "paged-cache feature — pass block_tables too")
    if pre_caches is not None and time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer: pre_caches apply to the context/"
            "prefill phase; at decode time the prefix already lives in "
            "cache_kvs (run prefill with pre_caches first)")
    if block_tables is not None:
        if time_step is None or seq_lens is None:
            raise ValueError(
                "fused_multi_transformer: the paged-cache path is decode-"
                "only — pass time_step and per-sequence seq_lens with "
                "block_tables")
        if not cache_kvs:
            raise ValueError(
                "fused_multi_transformer: block_tables without cache_kvs "
                "— the paged path needs the per-layer paged caches")
        xs = (x.data if hasattr(x, "data") else x).shape
        if len(xs) != 3 or (xs[1] != 1 and chunk_lens is None):
            raise ValueError(
                "fused_multi_transformer: paged decode takes one token "
                f"per sequence (x [B, 1, E]); got {list(xs)} — a multi-"
                "token chunk slab needs per-sequence chunk_lens")
        if attn_mask is not None:
            raise NotImplementedError(
                "fused_multi_transformer: attn_mask unsupported on the "
                "paged decode path")
        if ragged_work is None:
            # eager convenience: build the work list from concrete lens
            import numpy as _np
            from ....ops.pallas.paged_attention import (build_ragged_work,
                                                        default_pack)
            from ....core.tensor import Tensor as _T
            lens_c = _np.asarray(
                seq_lens.data if isinstance(seq_lens, _T) else seq_lens)
            tbl_c = _np.asarray(
                block_tables.data if isinstance(block_tables, _T)
                else block_tables)
            c0 = cache_kvs[0]
            bs_ = (c0.data if hasattr(c0, "data") else c0).shape[3]
            if chunk_lens is None:
                qls_c = _np.ones_like(lens_c)
                qkw = {}
            else:
                qls_c = _np.asarray(
                    chunk_lens.data if isinstance(chunk_lens, _T)
                    else chunk_lens)
                qkw = {"q_lens": qls_c}
            ragged_work = build_ragged_work(
                tbl_c, lens_c + qls_c, bs_,
                ragged_pack or default_pack(
                    lens_c.shape[0],
                    _ragged_group_q(qkv_weights, gqa_group_size,
                                    trans_qkvw)), **qkw)
        if len(ragged_work) == 4 and isinstance(ragged_work[0],
                                                (tuple, list)):
            # the full build_ragged_work result: the carried pack is
            # authoritative (the work list's group encoding depends on it)
            if ragged_pack is not None and ragged_pack != ragged_work[3]:
                raise ValueError(
                    f"ragged_pack={ragged_pack} conflicts with the work "
                    f"list (built with pack={ragged_work[3]})")
            ragged_pack = ragged_work[3]
            ragged_work = ragged_work[0]
    G = gqa_group_size if gqa_group_size and gqa_group_size > 0 else 0
    n_layers = len(qkv_weights)
    caches_in = cache_kvs if cache_kvs is not None else []
    pre_in = pre_caches if pre_caches is not None else []
    dq = _dequant or (lambda w, kind, li: w)
    # _mm(z2d, kind, li) -> z2d @ W[kind][li]: when provided (the Pallas
    # weight-only-quant serving path, ops/pallas/quant_matmul.py), the
    # four projection matmuls run the in-kernel-dequant GEMM instead of
    # dequantize-then-einsum — quantized bytes are all that leave HBM
    # _tp_reduce: the tensor-parallel serving hook (inference/tp_layout
    # Megatron split). Applied to the ROW-parallel matmul outputs —
    # attention out-projection and ffn2 — BEFORE their bias adds, where
    # each device holds a partial sum over its weight-row shard; inside
    # the engine's shard_map'd step it is a psum over the 'tp' axis
    # (two per layer), identity when serving single-chip
    tp_red = _tp_reduce or (lambda x: x)

    def impl(xa, lns, lnb, qkvw, qkvb, linw, linb, flns, flnb, f1w, f1b,
             f2w, f2b, caches, pres, rotary, tstep, mask, slens, qlens,
             tables_a, rwork, dkeys):
        b, s, e = xa.shape
        norm = (lambda h, sc, bi: _rms(h, epsilon, sc)) \
            if norm_type == "rmsnorm" else \
            (lambda h, sc, bi: _ln(h, epsilon, sc, bi))
        h = xa
        new_caches = []
        for li in range(n_layers):
            resid = h
            z = norm(h, lns[li], lnb[li] if lnb else None) \
                if pre_layer_norm else h
            if _mm is not None and trans_qkvw:
                qkv = _mm(z.reshape(b * s, e), qkvw[li], "qkv",
                          li).reshape((b, s) + _mm.qkv_out)
                if qkvb and qkvb[li] is not None:
                    qkv = qkv + qkvb[li][None, None]
                if G:
                    ht, hd = _mm.qkv_out
                    nh = ht - 2 * G
                    q = qkv[:, :, :nh]
                    k = qkv[:, :, nh:nh + G]
                    v = qkv[:, :, nh + G:]
                else:
                    nh, hd = _mm.qkv_out[1], _mm.qkv_out[2]
                    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            elif G:
                w = dq(qkvw[li], "qkv", li)
                # GQA packing (reference fused_transformer.py:1009 /
                # infermeta/fusion.cc gqa branch): weight [H + 2G, D, E]
                # — H query heads, then G key heads, then G value heads
                if not trans_qkvw:
                    w = jnp.transpose(w, (1, 2, 0))      # [E,H+2G,D] packed
                ht, hd = w.shape[0], w.shape[1]
                nh = ht - 2 * G
                qkv = jnp.einsum("bse,hde->bshd", z.astype(w.dtype), w)
                if qkvb and qkvb[li] is not None:
                    qkv = qkv + qkvb[li][None, None]
                q = qkv[:, :, :nh]                       # [B,S,H,D]
                k = qkv[:, :, nh:nh + G]                 # [B,S,G,D]
                v = qkv[:, :, nh + G:]
            else:
                w = dq(qkvw[li], "qkv", li)
                if not trans_qkvw:
                    # [E, 3, H, D] layout -> [3, H, D, E]
                    w = jnp.transpose(w, (1, 2, 3, 0))
                nh, hd = w.shape[1], w.shape[2]
                qkv = jnp.einsum("bse,thde->bsthd", z.astype(w.dtype), w)
                if qkvb and qkvb[li] is not None:
                    qkv = qkv + qkvb[li][None, None]
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            if rotary is not None:
                cos = rotary[0][:, 0][:, :, None, :]    # [B, S_rope, 1, D]
                sin = rotary[1][:, 0][:, :, None, :]
                if tstep is not None and slens is not None:
                    # ragged decode: each sequence sits at its OWN position
                    # (its current length), not a shared time step
                    ln = jnp.asarray(slens).reshape(-1)
                    bidx = jnp.arange(cos.shape[0]) \
                        if cos.shape[0] > 1 else jnp.zeros_like(ln)
                    if s == 1:
                        cos = cos[bidx, ln][:, None]    # [B, 1, 1, D]
                        sin = sin[bidx, ln][:, None]
                    else:
                        # chunked prefill: token column j of sequence b
                        # rotates at position lens[b] + j (clamped into
                        # the table for the padding columns past qlens)
                        posr = jnp.minimum(
                            ln[:, None] + jnp.arange(s)[None, :],
                            cos.shape[1] - 1)           # [B, C]
                        cos = cos[bidx[:, None], posr]  # [B, C, 1, D]
                        sin = sin[bidx[:, None], posr]
                elif tstep is not None:
                    pos = jnp.asarray(tstep).reshape(())
                    cos = jax.lax.dynamic_slice_in_dim(cos, pos, 1, 1)
                    sin = jax.lax.dynamic_slice_in_dim(sin, pos, 1, 1)
                else:
                    cos, sin = cos[:, :s], sin[:, :s]
                q, k = _apply_rope_pair(q, k, cos, sin,
                                        use_neox_rotary_style)
            scale = 1.0 / math.sqrt(hd)
            # grouped-attention geometry: kv heads g, queries-per-group r
            # (r == 1 and g == nh for MHA; the einsums below serve both —
            # no jnp.repeat materialisation of KV on the decode hot path)
            g_eff = G or nh
            r = nh // g_eff
            if tstep is not None and caches and tables_a is not None:
                # paged decode (continuous batching): append this step's
                # token (or prompt CHUNK) into the blocks owned by each
                # sequence starting at slot seq_lens, then run the ragged
                # Pallas kernel over the flattened work list — grid cost
                # scales with the sum of ACTUAL per-sequence KV blocks,
                # not B x max_blocks, and a whole prompt chunk rides one
                # kernel invocation next to the decode rows
                from ....ops.pallas.paged_attention import (
                    ragged_paged_attention, update_paged_kv_cache,
                    update_paged_kv_cache_chunk)
                cache = caches[li]             # [2, KVH, NB, BS, D]
                ln = jnp.asarray(slens).reshape(-1)
                if qlens is None:
                    kc, vc = update_paged_kv_cache(
                        cache[0], cache[1], k[:, 0], v[:, 0], tables_a,
                        ln)
                    ctx = ragged_paged_attention(
                        q[:, 0], kc, vc, tables_a, ln + 1, scale=scale,
                        work=(tuple(rwork), None, rwork[0].shape[0],
                              ragged_pack),
                        buffer_depth=kv_buffer_depth)
                    ctx = ctx[:, None].astype(xa.dtype)   # [B, 1, H, D]
                else:
                    ql = jnp.asarray(qlens).reshape(-1)
                    kc, vc = update_paged_kv_cache_chunk(
                        cache[0], cache[1], k, v, tables_a, ln, ql)
                    ctx = ragged_paged_attention(
                        q, kc, vc, tables_a, ln + ql, scale=scale,
                        work=(tuple(rwork), None, rwork[0].shape[0],
                              ragged_pack), q_lens=ql,
                        buffer_depth=kv_buffer_depth
                        ).astype(xa.dtype)                # [B, C, H, D]
                new_caches.append(jnp.stack([kc, vc]))
            elif tstep is not None and caches:
                # decode: append the new token, attend over the valid cache
                cache = caches[li]                 # [2, B, g, S_max, D]
                t = jnp.asarray(tstep).reshape(())
                smax = cache.shape[3]
                if slens is not None:
                    # ragged batch: per-sequence append at slot lens[b]
                    # (reference seq_lens contract, as in
                    # masked_multihead_attention); caller advances seq_lens
                    ln = jnp.asarray(slens).reshape(-1)
                    bidx = jnp.arange(b)
                    kc = cache[0].at[bidx, :, ln].set(k[:, 0])
                    vc = cache[1].at[bidx, :, ln].set(v[:, 0])
                    posm = (jnp.arange(smax)[None, None, None, None, :]
                            <= ln[:, None, None, None, None])
                else:
                    kc = jax.lax.dynamic_update_slice_in_dim(
                        cache[0], k.transpose(0, 2, 1, 3), t, axis=2)
                    vc = jax.lax.dynamic_update_slice_in_dim(
                        cache[1], v.transpose(0, 2, 1, 3), t, axis=2)
                    posm = jnp.arange(smax)[None, None, None, None, :] <= t
                qg = q.reshape(b, s, g_eff, r, hd)
                logits = jnp.einsum(
                    "bsgrd,bgtd->bgrst", qg.astype(jnp.float32),
                    kc.astype(jnp.float32)) * scale   # [B,g,r,1,S_max]
                if mask is not None:
                    logits = logits + mask[:, :, None].astype(logits.dtype)
                logits = jnp.where(posm, logits, NEG_INF_F)
                p = jax.nn.softmax(logits, axis=-1)
                ctx = jnp.einsum("bgrst,bgtd->bsgrd", p,
                                 vc.astype(jnp.float32)
                                 ).reshape(b, s, nh, hd).astype(xa.dtype)
                new_caches.append(jnp.stack([kc, vc]))
            else:
                # context/prefill: causal attention, fill cache [0:S];
                # pre_caches (prompt-prefix KV, reference pre_caches arg)
                # prepend their keys — every new row attends to the whole
                # prefix plus the causal part of the new tokens
                kk, vv = k, v
                s_pre = 0
                if pres:
                    pk, pv = pres[li][0], pres[li][1]  # [B, g, S_pre, D]
                    s_pre = pk.shape[2]
                    kk = jnp.concatenate(
                        [pk.transpose(0, 2, 1, 3), k], axis=1)
                    vv = jnp.concatenate(
                        [pv.transpose(0, 2, 1, 3), v], axis=1)
                qg = q.reshape(b, s, g_eff, r, hd)
                logits = jnp.einsum(
                    "bsgrd,btgd->bgrst", qg.astype(jnp.float32),
                    kk.astype(jnp.float32)) * scale   # [B,g,r,S,S_pre+S]
                causal = jnp.tril(jnp.ones((s, s), bool))
                if s_pre:
                    causal = jnp.concatenate(
                        [jnp.ones((s, s_pre), bool), causal], axis=1)
                causal = causal[None, None, None]
                if slens is not None:
                    # padded batch: keys at/after each row's true length
                    # must not contribute (reference seq_lens semantics)
                    valid = (jnp.arange(s)[None, :]
                             < jnp.asarray(slens).reshape(-1, 1))
                    if s_pre:
                        valid = jnp.concatenate(
                            [jnp.ones((b, s_pre), bool), valid], axis=1)
                    causal = causal & valid[:, None, None, None, :]
                if mask is not None:
                    logits = logits + mask[:, :, None].astype(logits.dtype)
                logits = jnp.where(causal, logits, NEG_INF_F)
                p = jax.nn.softmax(logits, axis=-1)
                ctx = jnp.einsum("bgrst,btgd->bsgrd", p,
                                 vv.astype(jnp.float32)
                                 ).reshape(b, s, nh, hd).astype(xa.dtype)
                if caches:
                    cache = caches[li]
                    kc = jax.lax.dynamic_update_slice_in_dim(
                        cache[0], kk.transpose(0, 2, 1, 3), 0, axis=2)
                    vc = jax.lax.dynamic_update_slice_in_dim(
                        cache[1], vv.transpose(0, 2, 1, 3), 0, axis=2)
                    new_caches.append(jnp.stack([kc, vc]))
            if _mm is not None:
                attn = _mm(ctx.reshape(b * s, nh * hd), linw[li],
                           "lin", li).reshape(b, s, -1)
            else:
                attn = ctx.reshape(b, s, nh * hd) @ dq(linw[li], "lin", li)
            attn = tp_red(attn)
            if linb and linb[li] is not None:
                attn = attn + linb[li]
            if training and dropout_rate:
                keep = jax.random.bernoulli(
                    dkeys[li], 1.0 - dropout_rate, attn.shape)
                attn = jnp.where(keep, attn / (1.0 - dropout_rate), 0.0) \
                    if mode == "upscale_in_train" else \
                    jnp.where(keep, attn, 0.0)
            h = resid * residual_alpha + attn
            if not pre_layer_norm:
                h = norm(h, lns[li], lnb[li] if lnb else None)
            resid2 = h
            z2 = norm(h, flns[li], flnb[li] if flnb else None) \
                if pre_layer_norm else h
            if _mm is not None:
                f1 = _mm(z2.reshape(b * s, -1), f1w[li], "f1",
                         li).reshape(b, s, -1)
            else:
                f1 = z2 @ dq(f1w[li], "f1", li)
            if f1b and f1b[li] is not None:
                f1 = f1 + f1b[li]
            if activation.endswith("glu"):
                a, g = jnp.split(f1, 2, axis=-1)
                act = jax.nn.silu if activation == "swiglu" else jax.nn.gelu
                f1 = act(a) * g
            elif activation == "relu":
                f1 = jax.nn.relu(f1)
            else:
                f1 = jax.nn.gelu(f1)
            if _mm is not None:
                f2 = _mm(f1.reshape(b * s, -1), f2w[li], "f2",
                         li).reshape(b, s, -1)
            else:
                f2 = f1 @ dq(f2w[li], "f2", li)
            f2 = tp_red(f2)
            if f2b and f2b[li] is not None:
                f2 = f2 + f2b[li]
            h = resid2 * residual_alpha + f2
            if not pre_layer_norm:
                h = norm(h, flns[li], flnb[li] if flnb else None)
        return tuple([h] + new_caches)

    out = apply_op(
        "fused_multi_transformer", impl,
        (x, list(ln_scales), list(ln_biases or []), list(qkv_weights),
         list(qkv_biases or []), list(linear_weights),
         list(linear_biases or []), list(ffn_ln_scales),
         list(ffn_ln_biases or []), list(ffn1_weights),
         list(ffn1_biases or []), list(ffn2_weights), list(ffn2_biases or []),
         list(caches_in), list(pre_in), rotary_embs, time_step, attn_mask,
         seq_lens, chunk_lens, block_tables,
         list(ragged_work) if ragged_work is not None else [],
         # per-layer dropout keys as input leaves (vjp-cacheable +
         # trace-safe, like the other fused ops)
         [_random.fresh_key_tensor() for _ in range(n_layers)]
         if training and dropout_rate else []),
        {}, differentiable=bool(training) and not caches_in)
    outs = out if isinstance(out, tuple) else (out,)
    h = outs[0]
    # dygraph reference semantics: caches mutate in place
    for cache_t, new_t in zip(caches_in, outs[1:]):
        if isinstance(cache_t, Tensor):
            cache_t._data = new_t._data
    return h


def fused_multi_transformer_int8(
        x, ln_scales, ln_biases, qkv_weights, qkv_scales, qkv_biases,
        linear_weights, linear_scales, linear_biases, ffn_ln_scales,
        ffn_ln_biases, ffn1_weights, ffn1_scales, ffn1_biases, ffn2_weights,
        ffn2_scales, ffn2_biases, **kwargs):
    """Weight-only-int8 variant (role of the reference's
    fused_multi_transformer_int8_kernel.cu): weights are int8 with
    per-output-channel scales; dequantisation happens inside the op, where
    XLA fuses the int8→bf16 convert+scale into the matmul's operand load —
    the TPU analogue of the CUDA kernel's dequant epilogue.

    Weight lists hold int8 tensors shaped as in fused_multi_transformer;
    each *_scales list holds the matching per-channel scale (last dim of
    the weight's output axis)."""
    from ....core.tensor import Tensor as _T
    scales = {"qkv": list(qkv_scales), "lin": list(linear_scales),
              "f1": list(ffn1_scales), "f2": list(ffn2_scales)}

    def dq(w, kind, li):
        sc = scales[kind][li]
        sc = sc.data if isinstance(sc, _T) else jnp.asarray(sc)
        if kind == "qkv":
            # [3, H, D, E] int8, scale per (3, H, D) output channel
            s3 = sc.reshape(w.shape[0], w.shape[1], w.shape[2], 1)
            return w.astype(jnp.float32) * s3
        return w.astype(jnp.float32) * sc[None, :]

    return fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, _dequant=dq, **kwargs)


def quantize_int4(w, axis=-1, group_size=None):
    """Pack a float weight into (packed int8 nibbles, scales) for
    fused_multi_transformer_int4. Symmetric per-channel (or per-group)
    absmax quantization along the INPUT axis `axis`; two consecutive
    int4 values pack into one int8 byte (low nibble first) along that
    axis, halving weight HBM vs int8.

    Returns (packed, scales): packed has `axis` halved; scales broadcast
    over `axis` (shape keeps other dims, axis -> n_groups or 1)."""
    import numpy as np
    a = np.asarray(w.data if hasattr(w, "data") else w, np.float32)
    a = np.moveaxis(a, axis, -1)
    n = a.shape[-1]
    if n % 2:
        raise ValueError("int4 packing needs an even axis length")
    g = group_size or n
    if n % g:
        raise ValueError("group_size must divide the quantized axis")
    grp = a.reshape(*a.shape[:-1], n // g, g)
    sc = np.abs(grp).max(-1, keepdims=True) / 7.0 + 1e-9
    q = np.clip(np.round(grp / sc), -8, 7).astype(np.int8)
    q = q.reshape(*a.shape[:-1], n)
    lo, hi = q[..., 0::2], q[..., 1::2]
    packed = ((hi.astype(np.uint8) << 4) |
              (lo.astype(np.uint8) & 0x0F)).astype(np.int8)
    packed = np.moveaxis(packed, -1, axis % a.ndim if axis >= 0 else axis)
    scales = np.moveaxis(sc[..., 0], -1, axis % a.ndim if axis >= 0
                         else axis)
    return packed, scales.astype(np.float32)


def _unpack_int4(p, axis=-1):
    """int8-packed nibbles -> int4 values (sign-extended), axis doubled."""
    u = p.astype(jnp.uint8)
    lo = (u & 0x0F).astype(jnp.int8)
    hi = (u >> 4).astype(jnp.int8)
    lo = jnp.where(lo >= 8, lo - 16, lo).astype(jnp.int8)
    hi = jnp.where(hi >= 8, hi - 16, hi).astype(jnp.int8)
    stacked = jnp.stack([lo, hi], axis=-1)         # [..., n/2, 2]
    out = stacked.reshape(*p.shape[:-1], p.shape[-1] * 2) \
        if axis in (-1, p.ndim - 1) else None
    if out is None:
        m = jnp.moveaxis(p, axis, -1)
        u = m.astype(jnp.uint8)
        lo = (u & 0x0F).astype(jnp.int8)
        hi = (u >> 4).astype(jnp.int8)
        lo = jnp.where(lo >= 8, lo - 16, lo)
        hi = jnp.where(hi >= 8, hi - 16, hi)
        out = jnp.stack([lo, hi], -1).reshape(*m.shape[:-1],
                                              m.shape[-1] * 2)
        out = jnp.moveaxis(out, -1, axis)
    return out


def fused_multi_transformer_int4(
        x, ln_scales, ln_biases, qkv_weights, qkv_scales, qkv_biases,
        linear_weights, linear_scales, linear_biases, ffn_ln_scales,
        ffn_ln_biases, ffn1_weights, ffn1_scales, ffn1_biases, ffn2_weights,
        ffn2_scales, ffn2_biases, **kwargs):
    """Weight-only-int4 variant — HALF the weight HBM of the reference's
    int8 tier (capability upgrade; the reference stops at int8). Weights
    are int8 bytes holding two packed nibbles along the INPUT (embed)
    axis with per-output-channel symmetric scales from `quantize_int4`;
    the unpack + dequant lowers into the matmul's operand load like the
    int8 path.

    Shapes: qkv [3, H, D, E/2] (+scale [3, H, D]); linear [H*D/2, E]
    packed on axis 0 (+scale [E]); ffn1 [E/2, F] (+scale [F]);
    ffn2 [F/2, E] (+scale [E])."""
    from ....core.tensor import Tensor as _T
    scales = {"qkv": list(qkv_scales), "lin": list(linear_scales),
              "f1": list(ffn1_scales), "f2": list(ffn2_scales)}

    def dq(w, kind, li):
        sc = scales[kind][li]
        sc = sc.data if isinstance(sc, _T) else jnp.asarray(sc)
        # quantize_int4's scales already broadcast against the unpacked
        # weight (qkv: [3,H,D,1] vs [3,H,D,E]; lin/f1/f2: [1,out] vs
        # [in,out])
        full = _unpack_int4(w, axis=-1 if kind == "qkv" else 0)
        return full.astype(jnp.float32) * sc

    return fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, _dequant=dq, **kwargs)


# -- cublasLt-epilogue tier (reference fused_matmul_bias.py:31,95,136 — on
# TPU the epilogue IS XLA fusion: bias-add and gelu/relu fuse into the
# matmul's result tiles, so these express intent and let the compiler do
# what cublasLt does by hand) --------------------------------------------

def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias-add in one compiled region (reference
    fused_gemm_epilogue_kernel.cu role)."""
    def impl(xv, yv, *rest):
        a = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        b = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = a @ b
        if rest:
            out = out + rest[0]
        return out

    args = (x, y) if bias is None else (x, y, bias)
    return apply_op("fused_matmul_bias", impl, args, {})


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Reference fused_matmul_bias.py:95 — linear via the epilogue path."""
    return fused_matmul_bias(x, weight, bias, False, transpose_weight, name)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation=None):
    """matmul + bias + gelu/relu epilogue (reference
    fused_matmul_bias.py:136)."""
    if activation not in (None, "none", "gelu", "relu"):
        raise ValueError(f"unsupported epilogue activation {activation}")

    def impl(xv, yv, bv):
        a = jnp.swapaxes(xv, -1, -2) if trans_x else xv
        b = jnp.swapaxes(yv, -1, -2) if trans_y else yv
        out = a @ b + bv
        if activation == "gelu":
            out = jax.nn.gelu(out, approximate=True)
        elif activation == "relu":
            out = jax.nn.relu(out)
        return out

    return apply_op("fused_linear_activation", impl, (x, y, bias), {})


def swiglu(x, y=None, name=None):
    """SwiGLU (reference swiglu.py:26): silu(x) * y; with y=None, x is
    chunked in half on the last axis. The pattern XLA fuses into the
    surrounding GEMMs (the reference has a dedicated CUDA kernel)."""
    if y is None:
        def impl(xv):
            a, b = jnp.split(xv, 2, axis=-1)
            return jax.nn.silu(a) * b
        return apply_op("swiglu", impl, (x,), {})

    def impl(xv, yv):
        return jax.nn.silu(xv) * yv
    return apply_op("swiglu", impl, (x, y), {})


# -- fused norm tier (reference fused_rms_norm.py:59, fused_layer_norm.py:61
# — norm(bias + residual + x) patterns with optional int8 quant of the
# normalized output) ------------------------------------------------------

def _maybe_quant(out, quant_scale, quant_round_type, quant_max_bound,
                 quant_min_bound):
    if quant_scale <= 0:
        return out
    q = out.astype(jnp.float32) * quant_max_bound * quant_scale
    if quant_round_type == 0:
        q = jnp.rint(q)  # round half to even
    else:
        q = jnp.where(q >= 0, jnp.floor(q + 0.5), jnp.ceil(q - 0.5))
    return jnp.clip(q, quant_min_bound, quant_max_bound).astype(jnp.int8)


def fused_rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
                   bias=None, residual=None, quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0):
    """RMSNorm(bias + residual + x) fused (reference fused_rms_norm.py:59).
    Returns (out, residual_out): residual_out is the pre-norm sum the next
    layer's residual branch consumes."""
    def impl(xv, w, *rest):
        it = iter(rest)
        b = next(it) if norm_bias is not None else None
        pb = next(it) if bias is not None else None
        res = next(it) if residual is not None else None
        h = xv
        if pb is not None:
            h = h + pb
        if res is not None:
            h = h + res
        red = tuple(range(begin_norm_axis, h.ndim))
        hf = h.astype(jnp.float32)
        inv = jax.lax.rsqrt(jnp.mean(hf * hf, axis=red, keepdims=True)
                            + epsilon)
        out = (hf * inv).astype(h.dtype) * w
        if b is not None:
            out = out + b
        out = _maybe_quant(out, quant_scale, quant_round_type,
                           quant_max_bound, quant_min_bound)
        return out, h

    args = [x, norm_weight]
    for t in (norm_bias, bias, residual):
        if t is not None:
            args.append(t)
    return apply_op("fused_rms_norm", impl, tuple(args), {})


def fused_layer_norm(x, norm_weight, norm_bias, epsilon, residual_alpha=1.0,
                     begin_norm_axis=1, bias=None, residual=None,
                     quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                     quant_min_bound=0):
    """LayerNorm(bias + residual_alpha*residual + x) fused (reference
    fused_layer_norm.py:61). With norm_weight=None and norm_bias=None the
    result is just the fused sum. Returns (out, residual_out)."""
    def impl(xv, *rest):
        it = iter(rest)
        w = next(it) if norm_weight is not None else None
        b = next(it) if norm_bias is not None else None
        pb = next(it) if bias is not None else None
        res = next(it) if residual is not None else None
        h = xv
        if pb is not None:
            h = h + pb
        if res is not None:
            h = h + residual_alpha * res
        if w is None and b is None:
            return h, h
        red = tuple(range(begin_norm_axis, h.ndim))
        hf = h.astype(jnp.float32)
        mu = jnp.mean(hf, axis=red, keepdims=True)
        var = jnp.mean((hf - mu) ** 2, axis=red, keepdims=True)
        out = ((hf - mu) * jax.lax.rsqrt(var + epsilon)).astype(h.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        out = _maybe_quant(out, quant_scale, quant_round_type,
                           quant_max_bound, quant_min_bound)
        return out, h

    args = [x]
    for t in (norm_weight, norm_bias, bias, residual):
        if t is not None:
            args.append(t)
    return apply_op("fused_layer_norm", impl, tuple(args), {})


# -- MoE + var-len attention tier ----------------------------------------

def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True):
    """Fused MoE FFN (reference fused_moe.py:20): gate -> top-k -> expert
    GLU-FFN -> weighted combine, one compiled region.

    TPU-native: instead of the reference's scatter-to-expert-buffers CUDA
    choreography, every expert's GEMM runs as one batched einsum over a
    dense one-hot combine weight — MXU-friendly static shapes, zero
    dynamic gathers; token routing resolves to the [tokens, experts]
    combine matrix (the same design as incubate/distributed/models/moe)."""
    if quant_method not in (None, "None", "none", "weight_only_int8"):
        raise NotImplementedError(
            f"fused_moe: quant_method={quant_method!r} unsupported "
            "(weight-only int8 via ffn*_scale, or float weights)")

    def impl(xv, gw, w1, w2, *rest):
        it = iter(rest)
        b1 = next(it) if ffn1_bias is not None else None
        b2 = next(it) if ffn2_bias is not None else None
        s1 = next(it) if ffn1_scale is not None else None
        s2 = next(it) if ffn2_scale is not None else None
        # weight-only dequant (reference ffn*_scale contract: one scale per
        # expert per out-channel); the cast+scale fuses into the einsum's
        # operand load like nn/quant.weight_only_linear
        if s1 is not None:
            w1 = w1.astype(jnp.float32) * s1.reshape(
                s1.shape[0], 1, -1).astype(jnp.float32)
        if s2 is not None:
            w2 = w2.astype(jnp.float32) * s2.reshape(
                s2.shape[0], 1, -1).astype(jnp.float32)
        B, S, D = xv.shape
        E = w1.shape[0]
        tokens = xv.reshape(B * S, D)
        # gate_weight per reference: [B, S, E] logits, or a [D, E] weight
        if gw.ndim == 3:
            logits = gw.reshape(B * S, E)
        else:
            logits = tokens.astype(jnp.float32) @ gw.astype(jnp.float32)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        combine = jnp.zeros((B * S, E), dtype=jnp.float32)
        combine = combine.at[jnp.arange(B * S)[:, None], topi].add(topv)
        # dense expert batch: [E, T, D] views weighted after the fact — the
        # GEMMs stay large and static; GSPMD shards E over the ep axis
        h = jnp.einsum("td,edf->etf", tokens, w1.astype(tokens.dtype))
        if b1 is not None:
            h = h + b1
        half = h.shape[-1] // 2
        h = jax.nn.silu(h[..., :half]) * h[..., half:] \
            if w2.shape[1] * 2 == h.shape[-1] else jax.nn.gelu(h)
        y = jnp.einsum("etf,efd->etd", h, w2.astype(h.dtype))
        if b2 is not None:
            y = y + b2
        out = jnp.einsum("etd,te->td", y.astype(jnp.float32), combine)
        return out.reshape(B, S, D).astype(xv.dtype)

    args = [x, gate_weight, ffn1_weight, ffn2_weight]
    for t in (ffn1_bias, ffn2_bias, ffn1_scale, ffn2_scale):
        if t is not None:
            args.append(t)
    return apply_op("fused_moe", impl, tuple(args), {})


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """Var-len attention over padded [B, H, S, D] tensors (reference
    variable_length_memory_efficient_attention.py:33, cutlass kernel).
    Per-sequence lengths become masks over the static padded shapes — the
    TPU answer to ragged batches (no dynamic shapes under jit)."""
    def impl(q, k, v, sl, kvl, *rest):
        m = rest[0] if mask is not None else None
        B, H, S, D = q.shape
        Skv = k.shape[2]
        sc = scale if scale is not None else 1.0 / math.sqrt(D)
        logits = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * sc
        q_pos = jnp.arange(S)[None, :]            # [1, S]
        kv_pos = jnp.arange(Skv)[None, :]         # [1, Skv]
        q_valid = q_pos < sl.reshape(B, 1)        # [B, S]
        kv_valid = kv_pos < kvl.reshape(B, 1)     # [B, Skv]
        neg = jnp.finfo(jnp.float32).min
        logits = jnp.where(kv_valid[:, None, None, :], logits, neg)
        if causal:
            cm = (jnp.arange(Skv)[None, :] - pre_cache_length
                  <= jnp.arange(S)[:, None])
            logits = jnp.where(cm[None, None], logits, neg)
        if m is not None:
            logits = logits + m.astype(jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhst,bhtd->bhsd", p.astype(v.dtype), v)
        return jnp.where(q_valid[:, None, :, None], out, 0)

    args = [query, key, value, seq_lens, kv_seq_lens]
    if mask is not None:
        args.append(mask)
    return apply_op("variable_length_memory_efficient_attention", impl,
                    tuple(args), {})


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size):
    """Max encoder/decoder lengths for block_multihead_attention
    (reference blha_get_max_len.py:26)."""
    def impl(enc, dec, _bsz):
        return (jnp.max(enc).astype(jnp.int32).reshape(1),
                jnp.max(dec).astype(jnp.int32).reshape(1))

    return apply_op("blha_get_max_len", impl,
                    (seq_lens_encoder, seq_lens_decoder, batch_size), {},
                    differentiable=False)
