"""Fused-op python bindings land here (reference: python/paddle/incubate/
nn/functional/). Populated by the fused/Pallas tier."""
