"""Calibration observers (reference: python/paddle/quantization/observers/
— AbsmaxObserver, EMD/MSE/hist/KL observers; each is a passthrough Layer
that records activation statistics and later reports a quant scale)."""
import numpy as np

from ..nn.layer import Layer


class BaseObserver(Layer):
    """Passthrough layer that accumulates statistics on forward."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1  # per-tensor

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return 0.0

    def forward(self, x):
        self._observe(x)
        return x

    def _observe(self, x):
        raise NotImplementedError

    def _qbound(self):
        return float(2 ** (self._quant_bits - 1) - 1)


class AbsmaxObserver(BaseObserver):
    """Running max of |x| (observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._absmax = 0.0

    def _observe(self, x):
        self._absmax = max(self._absmax,
                           float(np.abs(np.asarray(x.numpy())).max()))

    def scales(self):
        return max(self._absmax, 1e-9) / self._qbound()


class EMAObserver(BaseObserver):
    """Exponential moving average of per-batch absmax (observers/emd.py
    family; the QAT-friendly smoothed estimator)."""

    def __init__(self, quant_bits=8, momentum=0.9):
        super().__init__(quant_bits)
        self._momentum = momentum
        self._ema = None

    def _observe(self, x):
        m = float(np.abs(np.asarray(x.numpy())).max())
        self._ema = m if self._ema is None else \
            self._momentum * self._ema + (1 - self._momentum) * m

    def scales(self):
        return max(self._ema or 0.0, 1e-9) / self._qbound()


class PercentileObserver(BaseObserver):
    """Percentile of |x| over a histogram (observers/hist.py role) —
    clips outliers that would waste int8 range."""

    def __init__(self, quant_bits=8, percentile=99.9, bins=2048):
        super().__init__(quant_bits)
        self._percentile = percentile
        self._hist = np.zeros(bins)
        self._edges = None
        self._bins = bins

    def _observe(self, x):
        a = np.abs(np.asarray(x.numpy())).reshape(-1)
        hi = a.max() if a.size else 1.0
        if self._edges is None or hi > self._edges[-1]:
            # rescale histogram to the new range
            new_edges = np.linspace(0, max(hi, 1e-9), self._bins + 1)
            if self._edges is not None and self._hist.sum() > 0:
                centers = (self._edges[:-1] + self._edges[1:]) / 2
                idx = np.clip(np.searchsorted(new_edges, centers) - 1,
                              0, self._bins - 1)
                nh = np.zeros(self._bins)
                np.add.at(nh, idx, self._hist)
                self._hist = nh
            self._edges = new_edges
        idx = np.clip(np.searchsorted(self._edges, a) - 1, 0, self._bins - 1)
        np.add.at(self._hist, idx, 1)

    def scales(self):
        if self._edges is None or self._hist.sum() == 0:
            return 1e-9
        c = np.cumsum(self._hist) / self._hist.sum()
        i = int(np.searchsorted(c, self._percentile / 100.0))
        amax = self._edges[min(i + 1, self._bins)]
        return max(float(amax), 1e-9) / self._qbound()


class AbsmaxChannelWiseObserver(BaseObserver):
    """Per-output-channel absmax for weights (observers channel_wise)."""

    def __init__(self, quant_bits=8, quant_axis=0):
        super().__init__(quant_bits)
        self._axis = quant_axis
        self._absmax = None

    def quant_axis(self):
        return self._axis

    def _observe(self, x):
        a = np.abs(np.asarray(x.numpy()))
        red = tuple(i for i in range(a.ndim) if i != self._axis)
        m = a.max(axis=red) if red else a
        self._absmax = m if self._absmax is None else np.maximum(
            self._absmax, m)

    def scales(self):
        return np.maximum(self._absmax, 1e-9) / self._qbound()


class GroupWiseWeightObserver(AbsmaxChannelWiseObserver):
    """Group-wise absmax weight observer (reference
    quantization/observers/groupwise.py): channels along `quant_axis` are
    split into groups of `group_size`; one scale per group — the statistics
    tier behind group-quantized weight_only_linear (nn/quant.py
    group_size=64/128)."""

    def __init__(self, quant_bits=8, quant_axis=0, group_size=128):
        super().__init__(quant_bits, quant_axis)
        self._group_size = group_size

    def group_size(self):
        return self._group_size

    def _observe(self, x):
        a = np.abs(np.asarray(x.numpy()))
        if a.ndim < 2:
            return super()._observe(x)
        # group along quant_axis: [n_groups, group_size, rest...] absmax
        a = np.moveaxis(a, self._axis, 0)
        n = a.shape[0]
        g = self._group_size
        pad = (-n) % g
        if pad:
            a = np.concatenate([a, np.zeros((pad,) + a.shape[1:])], 0)
        m = a.reshape(-1, g, *a.shape[1:]).max(axis=tuple(
            range(1, a.ndim + 1)))
        self._absmax = m if self._absmax is None else np.maximum(
            self._absmax, m)
