"""QuantConfig (reference: python/paddle/quantization/config.py — maps
layers/types/names to (activation, weight) quanter factories)."""
import copy


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_act = activation
        self._global_weight = weight
        self._layer_cfg = {}   # id(layer) -> (act, weight)
        self._type_cfg = {}    # type -> (act, weight)
        self._name_cfg = {}    # layer name -> (act, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for ly in layers:
            self._layer_cfg[id(ly)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) \
            else [layer_name]
        for n in names:
            self._name_cfg[n] = (activation, weight)

    def config_for(self, layer, name=""):
        """Resolution order: per-layer > per-name > per-type > global
        (config.py _get_config_by_layer)."""
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        if name in self._name_cfg:
            return self._name_cfg[name]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        return (self._global_act, self._global_weight)

    def copy(self):
        return copy.copy(self)
