"""Fake quanters for QAT (reference: python/paddle/quantization/quanters/
abs_max.py FakeQuanterWithAbsMaxObserver — simulated quantization in the
forward, straight-through estimator in the backward).

STE lowering: q(x) = x + stop_gradient(fake_quant(x) - x), so the tape
sees identity for in-range values; the dispatch tape differentiates it
without a custom VJP."""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..nn.layer import Layer


def quantize(x, scale, quant_bits=8, quant_axis=-1):
    """Real int quantization: round(x/scale) clipped to int range."""
    bound = 2 ** (quant_bits - 1) - 1

    def impl(a, s):
        if quant_axis >= 0 and np.ndim(s) > 0:
            shape = [1] * a.ndim
            shape[quant_axis] = -1
            s = s.reshape(shape)
        return jnp.clip(jnp.round(a / s), -bound - 1, bound).astype(jnp.int8)

    return apply_op("quantize_linear", impl, (x, scale), {},
                    differentiable=False)


def dequantize(x, scale, quant_axis=-1):
    def impl(a, s):
        if quant_axis >= 0 and np.ndim(s) > 0:
            shape = [1] * a.ndim
            shape[quant_axis] = -1
            s = s.reshape(shape)
        return a.astype(jnp.float32) * s

    return apply_op("dequantize_linear", impl, (x, scale), {},
                    differentiable=False)


def fake_quant(x, scale, quant_bits=8, quant_axis=-1):
    """Quantize-dequantize with straight-through gradient."""
    bound = 2 ** (quant_bits - 1) - 1

    def impl(a, s):
        if quant_axis >= 0 and np.ndim(s) > 0:
            shape = [1] * a.ndim
            shape[quant_axis] = -1
            s = s.reshape(shape)
        q = jnp.clip(jnp.round(a / s), -bound - 1, bound) * s
        return a + jax.lax.stop_gradient(q - a)

    return apply_op("fake_quantize_dequantize", impl, (x, scale), {})


class BaseQuanter(Layer):
    """Quanter ABC (reference quantization.base_quanter.BaseQuanter):
    a Layer that fake-quantizes its input and reports scales/bits."""

    def forward(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None

    def quant_axis(self):
        return None

    def bit_length(self):
        return 8


class FakeQuanterWithAbsMax(BaseQuanter):
    """QAT activation/weight quanter: tracks absmax (EMA for activations,
    current for weights) and applies fake quant every forward
    (quanters/abs_max.py FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, quant_bits=8, moving_rate=0.9, per_batch=True):
        super().__init__()
        self._quant_bits = quant_bits
        self._moving_rate = moving_rate
        self._per_batch = per_batch
        self._ema = None

    def bit_length(self):
        return self._quant_bits

    def scales(self):
        bound = 2 ** (self._quant_bits - 1) - 1
        return max(self._ema or 0.0, 1e-9) / bound

    def forward(self, x):
        # statistics update is an eager/training-time side effect; inside
        # jit (tracers) or eval the frozen scale is used
        if self.training and not _is_tracer(x):
            m = float(np.abs(np.asarray(x.data)).max())
            self._ema = m if self._ema is None else (
                self._moving_rate * self._ema
                + (1 - self._moving_rate) * m)
        from ..core.tensor import to_tensor
        scale = to_tensor(np.float32(self.scales()))
        return fake_quant(x, scale, self._quant_bits)


def _is_tracer(x):
    import jax.core
    return isinstance(getattr(x, "data", x), jax.core.Tracer)


def quanter(name="FakeQuanterWithAbsMax", **kwargs):
    """Factory helper mirroring paddle.quantization.quanter registry."""
    table = {"FakeQuanterWithAbsMax": FakeQuanterWithAbsMax}
    cls = table[name]
    return lambda: cls(**kwargs)


class FakeQuanterWithAbsMaxObserver(FakeQuanterWithAbsMax):
    """Reference quanters/abs_max.py FakeQuanterWithAbsMaxObserver — the
    factory-named moving-average absmax quanter. Same mechanism as
    FakeQuanterWithAbsMax; kept as its own class so configs addressing the
    reference name map 1:1."""

    def __init__(self, moving_rate=0.9, quant_bits=8, dtype="float32",
                 name=None):
        super().__init__(quant_bits=quant_bits, moving_rate=moving_rate)
