"""paddle.quantization parity (reference: python/paddle/quantization/ —
PTQ/QAT framework with observers and quanters; SURVEY.md §2.10)."""
from .config import QuantConfig
from .observers import (BaseObserver, AbsmaxObserver, EMAObserver,
                        PercentileObserver, AbsmaxChannelWiseObserver,
                        GroupWiseWeightObserver)
from .quanters import (FakeQuanterWithAbsMax, FakeQuanterWithAbsMaxObserver,
                       fake_quant, quantize,
                       dequantize, quanter)
from .qat import (QAT, PTQ, QuantedLinear, QuantedConv2D,
                  InferQuantedLinear)

__all__ = [
    "QuantConfig", "BaseObserver", "AbsmaxObserver", "EMAObserver",
    "PercentileObserver", "AbsmaxChannelWiseObserver",
    "GroupWiseWeightObserver", "FakeQuanterWithAbsMaxObserver",
    "FakeQuanterWithAbsMax", "fake_quant", "quantize", "dequantize",
    "quanter", "QAT", "PTQ", "QuantedLinear", "QuantedConv2D",
    "InferQuantedLinear",
]

from .quanters import BaseQuanter  # noqa: F401,E402
