"""QAT / PTQ drivers (reference: python/paddle/quantization/{qat,ptq}.py —
QAT.quantize wraps conv/linear with fake-quant layers; PTQ.quantize inserts
observers, then convert() freezes scales into quantized inference layers)."""
import numpy as np

from .. import nn
from ..core.dispatch import apply_op
from ..core.tensor import to_tensor
from ..nn.layer import Layer
from .quanters import FakeQuanterWithAbsMax, fake_quant, quantize, dequantize


class QuantedLinear(Layer):
    """Linear with fake-quantized weight + activation (QAT simulation;
    reference nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, linear, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = linear
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        from ..nn import functional as F
        return F.linear(x, w, self._inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, conv, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = conv
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        from ..nn import functional as F
        c = self._inner
        return F.conv2d(x, w, c.bias, stride=c.stride, padding=c.padding,
                        dilation=c.dilation, groups=c.groups)


class InferQuantedLinear(Layer):
    """Converted inference layer: int8 weight + f32 scale, dequantized at
    matmul time (weight-only int8 — the TPU-relevant deployment mode;
    reference onnx_format convert path)."""

    def __init__(self, linear, weight_scale, quant_bits=8):
        super().__init__()
        w = linear.weight
        scale = to_tensor(np.float32(weight_scale))
        self.qweight = quantize(w, scale, quant_bits)
        self.scale = scale
        self.bias = linear.bias

    def forward(self, x):
        w = dequantize(self.qweight, self.scale)
        from ..nn import functional as F
        return F.linear(x, w, self.bias)


_DEFAULT_QAT_TYPES = (nn.Linear, nn.Conv2D)


def _wrap_layer(layer, act_q, w_q):
    if isinstance(layer, nn.Linear):
        return QuantedLinear(layer, act_q() if act_q else None,
                             w_q() if w_q else None)
    if isinstance(layer, nn.Conv2D):
        return QuantedConv2D(layer, act_q() if act_q else None,
                             w_q() if w_q else None)
    return None


def _replace_children(model, fn, prefix=""):
    for name, child in list(model._sub_layers.items()):
        full = f"{prefix}.{name}" if prefix else name
        new = fn(child, full)
        if new is not None:
            model._sub_layers[name] = new
        else:
            _replace_children(child, fn, full)


def _resolve_configs(config, model):
    """Resolve every sub-layer's (act, weight) config against the ORIGINAL
    model by qualified name. Per-layer configs key on id(layer), which a
    deepcopy would invalidate — so resolution must happen pre-copy."""
    resolved = {}

    def walk(m, prefix=""):
        for name, child in m._sub_layers.items():
            full = f"{prefix}.{name}" if prefix else name
            resolved[full] = config.config_for(child, full)
            walk(child, full)

    walk(model)
    return resolved


class QAT:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        resolved = _resolve_configs(self._config, model)
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def fn(layer, name):
            act_q, w_q = resolved.get(name, (None, None))
            if act_q is None and w_q is None:
                return None
            return _wrap_layer(layer, act_q, w_q)

        _replace_children(model, fn)
        return model

    def convert(self, model, inplace=False):
        """Freeze fake-quant scales into inference int8 layers."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def fn(layer, name):
            if isinstance(layer, QuantedLinear) and layer.weight_quanter:
                # recompute weight scale from the current weights
                w = np.abs(np.asarray(layer._inner.weight.numpy())).max()
                bound = 2 ** (layer.weight_quanter.bit_length() - 1) - 1
                return InferQuantedLinear(layer._inner, w / bound)
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                return layer._inner
            return None

        _replace_children(model, fn)
        return model


class PTQ:
    """Post-training quantization: insert observers, calibrate by running
    forwards, then convert to quantized inference layers."""

    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        resolved = _resolve_configs(self._config, model)
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def fn(layer, name):
            act_f, w_f = resolved.get(name, (None, None))
            if act_f is None and w_f is None:
                return None
            if isinstance(layer, _DEFAULT_QAT_TYPES):
                wrapped = _wrap_layer(
                    layer, act_f, None)
                if w_f is not None:
                    obs = w_f()
                    obs(layer.weight)       # weights observable immediately
                    wrapped.weight_quanter = None
                    wrapped._weight_observer = obs
                return wrapped
            return None

        _replace_children(model, fn)
        return model

    def convert(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def fn(layer, name):
            if isinstance(layer, QuantedLinear):
                obs = getattr(layer, "_weight_observer", None)
                if obs is not None:
                    return InferQuantedLinear(layer._inner,
                                              float(np.max(obs.scales())),
                                              obs.bit_length())
                return layer._inner
            if isinstance(layer, QuantedConv2D):
                return layer._inner
            return None

        _replace_children(model, fn)
        return model
