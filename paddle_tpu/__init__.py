"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference surveyed in /root/repo/SURVEY.md).

Architecture (not a port — see SURVEY.md §7):
  - storage/compute: jax.Array over PJRT; every op is a jnp/jax kernel that
    XLA compiles and fuses (replaces phi kernels + CINN).
  - eager autograd: tape of jax.vjp closures (replaces paddle/fluid/eager).
  - traced path: paddle_tpu.jit traces the same ops under jax.jit/pjit
    (replaces PIR + interpreter).
  - distributed: mesh-first (jax.sharding) — paddle_tpu.distributed.
"""
__version__ = "0.1.0"

from .core import (
    Tensor, Parameter, to_tensor, no_grad, enable_grad, is_grad_enabled,
    set_grad_enabled,
    float16, float32, float64, bfloat16, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128,
    set_device, get_device, device_count, is_compiled_with_tpu,
    seed, get_rng_state, set_rng_state,
)
from .core.autograd import grad
from .core.device import is_compiled_with_cuda
from .core.selected_rows import (SelectedRows, StringTensor, strings_empty,
                                 strings_lower, strings_upper)

# functional op surface (YAML-driven)
from .ops import *  # noqa: F401,F403
from . import ops
from .ops import OP_TABLE

from . import linalg
from . import ops as tensor  # paddle.tensor namespace alias

# framework-level namespaces are imported lazily below to keep import cheap
from . import nn
from . import optimizer
from . import io
from . import vision
from . import metric
from . import amp
from . import jit
from . import static
from . import distributed
from . import autograd
from . import distribution
from . import hapi
from . import profiler
from . import observability
from . import incubate
from . import device
from . import sparse
from . import fft
from . import signal
from . import quantization
from . import inference
from . import geometric
from . import audio
from . import text
from . import onnx
from . import hub
from .hapi import Model, summary
from .hapi.flops import flops
from .framework import save, load, set_default_dtype, get_default_dtype
from .framework.compat import *  # noqa: F401,F403 — dtype/Place/dlpack surface
from .framework.compat import batch  # shadowed-by-design helper
from .utils.flags import set_flags, get_flags
from .nn import ParamAttr
from .nn.functional import pdist
from .distributed.parallel import DataParallel

# paddle.bool is the dtype (shadows the builtin inside this namespace only,
# matching the reference's paddle.bool)
globals()["bool"] = bool_


# top-level forms of the random in-place fills (paddle.normal_(x, ...) ==
# x.normal_(...))
def normal_(x, mean=0.0, std=1.0):
    return x.normal_(mean, std)


def log_normal_(x, mean=1.0, std=2.0):
    return x.log_normal_(mean, std)


def bernoulli_(x, p=0.5):
    return x.bernoulli_(p)


def cauchy_(x, loc=0, scale=1):
    return x.cauchy_(loc, scale)


def geometric_(x, probs):
    return x.geometric_(probs)

import jax as _jax


def is_tensor(x):
    return isinstance(x, Tensor)


def numel(x):
    return to_tensor(x.size)


def shape(x):
    return to_tensor(x.shape, dtype="int64")


def rank(x):
    return to_tensor(x.ndim)


def device_get(x):
    return x.cpu()


def synchronize():
    """Block until all dispatched device work completes (reference:
    paddle.device.synchronize / cudaDeviceSynchronize)."""
    _jax.effects_barrier()


def enable_static():
    """Enter static-graph mode (reference paddle.enable_static): ops on
    feed-connected tensors are recorded into the default Program for
    Executor.run replay (static.program recorder)."""
    from . import static as _static
    from .static import program as _prog
    from .core import dispatch as _dispatch
    _static._static_mode = True
    _dispatch.set_static_recorder(
        _prog._make_recorder(_prog.default_main_program()))


def disable_static(place=None):
    """Back to dygraph (the default mode)."""
    from . import static as _static
    from .core import dispatch as _dispatch
    _static._static_mode = False
    _dispatch.set_static_recorder(None)


# in_dynamic_mode comes from framework.compat (star import above)


# late-bound Tensor methods that need linalg/signal modules loaded
from .core.tensor import _attach_extra_methods as _aem
_aem()
del _aem
