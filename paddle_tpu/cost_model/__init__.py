"""paddle.cost_model parity (reference python/paddle/cost_model/
cost_model.py:33 — CostModel.profile_measure runs a static program under
the profiler and reports per-op cost).

Here profile_measure executes the recorded static Program through the
Executor with the host tracer active and returns wall-time (the
whole-program XLA executable is the schedulable unit on TPU — per-op cost
splits are what the profiler's chrome trace shows)."""
import time

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def build_program(self):
        """A tiny fc program pair, as the reference's example builder."""
        from .. import static
        import paddle_tpu as paddle

        paddle.enable_static()
        main_program = static.Program()
        startup_program = static.Program()
        with static.program_guard(main_program, startup_program):
            data = static.data(name="X", shape=[10, 1], dtype="float32")
            hidden = static.nn.fc(data, 10)
            static.nn.fc(hidden, 10)
        paddle.disable_static()
        return startup_program, main_program

    def profile_measure(self, startup_program, main_program, device="gpu",
                        fetch_cost_list=("time",)):
        """Run the program once for warmup/compile, then measure; returns
        {"time": ms, "fetches": [...]} (reference returns cost via the
        profiler protobuf)."""
        from .. import static
        import paddle_tpu as paddle

        paddle.enable_static()
        try:
            exe = static.Executor()
            exe.run(startup_program)
            feeds = {}
            for var in getattr(main_program, "feed_names", lambda: [])() \
                    if callable(getattr(main_program, "feed_names", None)) \
                    else []:
                feeds[var] = np.random.random((10, 1)).astype("float32")
            # warmup compiles; the measured run reuses the executable
            try:
                exe.run(main_program, feed=feeds or None)
            except Exception:
                feeds = {"X": np.random.random((10, 1)).astype("float32")}
                exe.run(main_program, feed=feeds)
            t0 = time.perf_counter()
            exe.run(main_program, feed=feeds or None)
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
        finally:
            paddle.disable_static()
        return {"time": elapsed_ms, "fetch_cost_list": list(fetch_cost_list)}
