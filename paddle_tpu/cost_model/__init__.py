"""paddle.cost_model parity (reference python/paddle/cost_model/
cost_model.py:33 — CostModel.profile_measure runs a static program under
the profiler and reports per-op cost), rebuilt on the observability cost
catalog (observability/costs.py).

The whole-program XLA executable is the schedulable unit on TPU, and the
static Executor already AOT-compiles and caches it
(static/program.py: ``jax.jit(...).lower(arrays).compile()``) — so the
compiled artifacts carry XLA's own cost/memory analyses for free.
``profile_measure`` now reports, per compiled program: wall time,
cost-analysis FLOPs and bytes accessed, and the memory-analysis
argument/output/temp/peak-HBM sizes — the same catalog entries (and
``program_flops{program}`` / ``program_bytes{program}`` /
``program_peak_hbm{program}`` gauges) the serving and pretrain dispatch
paths feed."""
import time

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def build_program(self):
        """A tiny fc program pair, as the reference's example builder."""
        from .. import static
        import paddle_tpu as paddle

        paddle.enable_static()
        main_program = static.Program()
        startup_program = static.Program()
        with static.program_guard(main_program, startup_program):
            data = static.data(name="X", shape=[10, 1], dtype="float32")
            hidden = static.nn.fc(data, 10)
            static.nn.fc(hidden, 10)
        paddle.disable_static()
        return startup_program, main_program

    def profile_measure(self, startup_program, main_program, device="gpu",
                        fetch_cost_list=("time",)):
        """Run the program once for warmup/compile, then measure; returns
        {"time": ms, "fetch_cost_list": [...], "programs": {name:
        {flops, bytes_accessed, peak_hbm, arg_bytes, out_bytes,
        temp_bytes, ...}}} — the per-program rows come straight from the
        Executor's cached XLA executables through the cost catalog
        (reference returns cost via the profiler protobuf)."""
        from .. import static
        from ..observability import costs as _costs
        import paddle_tpu as paddle

        paddle.enable_static()
        try:
            exe = static.Executor()
            exe.run(startup_program)
            feeds = {}
            for var in getattr(main_program, "feed_names", lambda: [])() \
                    if callable(getattr(main_program, "feed_names", None)) \
                    else []:
                feeds[var] = np.random.random((10, 1)).astype("float32")
            # fetch EVERY terminal output (produced, never consumed by a
            # later op — not just the last op's: a program with two
            # independent heads must keep both): an empty fetch list
            # would let XLA dead-code-eliminate the whole module and the
            # cost analysis would (truthfully) report a zero-flop program
            fetch = []
            ops = getattr(main_program, "ops", None) or []
            consumed = {id(t) for rec in ops for _, t in rec.tensor_slots}
            seen = set()
            for rec in ops:
                for t in rec.out_tensors:
                    if id(t) not in consumed and id(t) not in seen:
                        seen.add(id(t))
                        fetch.append(t)
            # warmup compiles; the measured run reuses the executable
            try:
                exe.run(main_program, feed=feeds or None,
                        fetch_list=fetch)
            except Exception:
                feeds = {"X": np.random.random((10, 1)).astype("float32")}
                exe.run(main_program, feed=feeds, fetch_list=fetch)
            t0 = time.perf_counter()
            exe.run(main_program, feed=feeds or None, fetch_list=fetch)
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            # the Executor's executable cache holds the real compiled
            # artifacts: catalog every one (cost_analysis/memory_analysis
            # are graceful no-ops on backends lacking them)
            catalog = _costs.get_cost_catalog()
            programs = {}
            compiled = getattr(main_program, "_compiled", {})
            for i, executable in enumerate(compiled.values()):
                name = "static_program" if len(compiled) == 1 \
                    else f"static_program_{i}"
                entry = catalog.analyze_compiled(name, executable,
                                                 source="static")
                if entry is not None:
                    programs[name] = entry
        finally:
            paddle.disable_static()
        return {"time": elapsed_ms,
                "fetch_cost_list": list(fetch_cost_list),
                "programs": programs}
