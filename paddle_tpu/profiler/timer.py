"""Throughput benchmark timer (reference: python/paddle/profiler/timer.py —
`Benchmark` with reader/batch cost and ips, hapi hooks `benchmark()`)."""
import time


class _Stat:
    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.last = 0.0

    def add(self, v):
        self.total += v
        self.count += 1
        self.last = v

    @property
    def avg(self):
        return self.total / max(self.count, 1)


class Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self._step_start = None
        self._reader_start = None
        self.batch_cost = _Stat()
        self.reader_cost = _Stat()
        self.ips = _Stat()
        self.steps = 0

    def begin(self):
        self._step_start = time.perf_counter()
        self._reader_start = self._step_start

    def before_reader(self):
        self._reader_start = time.perf_counter()

    def after_reader(self):
        if self._reader_start is not None:
            self.reader_cost.add(time.perf_counter() - self._reader_start)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._step_start is not None:
            dur = now - self._step_start
            self.batch_cost.add(dur)
            if num_samples:
                self.ips.add(num_samples / dur)
            self.steps += 1
        self._step_start = now

    def end(self):
        self._step_start = None

    def step_info(self, unit=None):
        u = unit or "samples"
        msg = (f"batch_cost: {self.batch_cost.last:.5f} s "
               f"(avg {self.batch_cost.avg:.5f} s)")
        if self.reader_cost.count:
            msg += f", reader_cost: {self.reader_cost.avg:.5f} s"
        if self.ips.count:
            msg += f", ips: {self.ips.last:.2f} {u}/s"
        return msg


_global_benchmark = Benchmark()


def benchmark():
    return _global_benchmark
