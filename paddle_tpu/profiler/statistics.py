"""Summary statistics over host events (reference:
python/paddle/profiler/profiler_statistic.py summary tables)."""
from collections import defaultdict


class EventStat:
    __slots__ = ("name", "calls", "total_us", "max_us", "min_us")

    def __init__(self, name):
        self.name = name
        self.calls = 0
        self.total_us = 0.0
        self.max_us = 0.0
        self.min_us = float("inf")

    def add(self, dur_us):
        self.calls += 1
        self.total_us += dur_us
        self.max_us = max(self.max_us, dur_us)
        self.min_us = min(self.min_us, dur_us)

    @property
    def avg_us(self):
        return self.total_us / max(self.calls, 1)


class SummaryView:
    def __init__(self, by_name, by_type):
        self.by_name = by_name      # {name: EventStat}
        self.by_type = by_type      # {TracerEventType: EventStat}

    def items_sorted(self):
        return sorted(self.by_name.values(), key=lambda s: -s.total_us)


def build_summary(events):
    by_name = {}
    by_type = {}
    for name, etype, ts, dur, tid in events:
        s = by_name.get(name)
        if s is None:
            s = by_name[name] = EventStat(name)
        s.add(dur)
        t = by_type.get(etype)
        if t is None:
            t = by_type[etype] = EventStat(etype.name)
        t.add(dur)
    return SummaryView(by_name, by_type)


def print_summary(summary, time_unit="ms", max_rows=30):
    div = {"s": 1e6, "ms": 1e3, "us": 1.0}[time_unit]
    header = (f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
              f"{'Avg(' + time_unit + ')':>12}{'Max(' + time_unit + ')':>12}")
    print("-" * len(header))
    print(header)
    print("-" * len(header))
    for s in summary.items_sorted()[:max_rows]:
        print(f"{s.name[:39]:<40}{s.calls:>8}{s.total_us / div:>14.3f}"
              f"{s.avg_us / div:>12.3f}{s.max_us / div:>12.3f}")
    print("-" * len(header))
