"""Profiler (reference: paddle/fluid/platform/profiler/ host tracer +
python/paddle/profiler/profiler.py:358 — scheduler windows, RecordEvent
ranges, chrome-trace export, summary tables).

TPU-native split: host ranges are recorded by this module's tracer (the
RecordEvent role of paddle/fluid/platform/profiler/common_event.h); device
activity comes from the XLA/PJRT profiler (jax.profiler traces, the CUPTI
analogue of paddle/fluid/platform/profiler/cuda_tracer.cc) when a
tensorboard dir is given. The chrome-trace export contract is kept
(chrometracing_logger.cc)."""
import contextlib
import enum
import json
import os
import threading
import time

from ..core import dispatch as _dispatch
from .statistics import SummaryView, build_summary, print_summary
from .timer import Benchmark, benchmark

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "TracerEventType", "make_scheduler", "export_chrome_tracing",
    "export_protobuf", "load_profiler_result", "SummaryView", "Benchmark",
    "benchmark",
]


class SortedKeys(enum.Enum):
    """Summary-table sort keys (reference profiler.SortedKeys)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class TracerEventType(enum.Enum):
    Operator = 0
    Dataloader = 1
    ProfileStep = 2
    Forward = 3
    Backward = 4
    Optimization = 5
    Communication = 6
    PythonOp = 7
    PythonUserDefined = 8
    UserDefined = 9


class _HostTracer:
    """Append-only host event buffer (pure-Python fallback)."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def record(self, name, etype, ts_us, dur_us, tid):
        with self._lock:
            self.events.append((name, etype, ts_us, dur_us, tid))

    def drain(self):
        """Take-and-clear: uniform snapshot contract with the native
        tracer, whose ring drain is destructive by construction."""
        with self._lock:
            out, self.events = self.events, []
        return out

    def clear(self):
        with self._lock:
            self.events = []


class _NativeHostTracer:
    """Host ranges buffered by the native C++ ring buffer
    (paddle_tpu/native/src/tracer.cc — the host_tracer.cc role): the record
    hot path is a single ctypes call into an interned-name ring; events are
    drained and parsed only at stop/export time."""

    def __init__(self, lib, capacity=1 << 20):
        self._n = lib
        self._n.pt_trace_enable(capacity)

    def record(self, name, etype, ts_us, dur_us, tid):
        # names are arbitrary user strings; keep the TSV wire format parseable
        if "\t" in name or "\n" in name:
            name = name.replace("\t", " ").replace("\n", " ")
        self._n.pt_trace_record(name.encode(), etype.value, ts_us, dur_us,
                                tid)

    @property
    def events(self):
        import ctypes
        # size-then-fill can race with concurrent recording; retry until the
        # fill call reports it fit
        pad = 4096
        while True:
            need = self._n.pt_trace_drain(None, 0, 0)
            buf = ctypes.create_string_buffer(need + pad)
            got = self._n.pt_trace_drain(buf, len(buf), 0)
            if got < len(buf) - 1:
                break
            pad *= 4
        out = []
        for line in buf.value.decode().splitlines():
            name, etype, ts, dur, tid = line.rsplit("\t", 4)
            out.append((name, TracerEventType(int(etype)), float(ts),
                        float(dur), int(tid)))
        return out

    def drain(self):
        """Reading the native ring IS the drain (pt_trace_drain empties
        it); alias so both tracers share one snapshot contract."""
        return self.events

    def clear(self):
        self._n.pt_trace_clear()


def _make_tracer():
    try:
        from .. import native as _native
        if _native.AVAILABLE:
            return _NativeHostTracer(_native.LIB)
    except Exception:
        pass
    return _HostTracer()


_tracer = _make_tracer()
_active_profiler = None


class RecordEvent:
    """User/host range (reference: python/paddle/profiler/utils.py
    RecordEvent over platform::RecordEvent)."""

    def __init__(self, name, event_type=TracerEventType.PythonUserDefined):
        self.name = name
        self.event_type = event_type
        self._begin = None

    def begin(self):
        self._begin = time.perf_counter()

    def end(self):
        if self._begin is None:
            return
        if _active_profiler is not None and _active_profiler._recording:
            end = time.perf_counter()
            _tracer.record(self.name, self.event_type,
                           self._begin * 1e6, (end - self._begin) * 1e6,
                           threading.get_ident())
        self._begin = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def _op_tracer_ctx(name):
    return RecordEvent(name, TracerEventType.Operator)


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """State machine over step numbers (reference profiler.py make_scheduler):
    skip_first CLOSEDs, then cycles of [closed CLOSED, ready READY, record
    RECORD (last step RECORD_AND_RETURN)], `repeat` times (0 = forever)."""
    if closed < 0 or ready < 0 or record <= 0:
        raise ValueError("closed/ready >= 0 and record > 0 required")
    span = closed + ready + record

    def fn(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * span:
            return ProfilerState.CLOSED
        pos = step % span
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == span - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def _default_scheduler(step):
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready handler writing chrome://tracing json."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof):
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time() * 1000)}.paddle_trace.json")
        prof._export_chrome(path)
        prof._last_export_path = path

    return handler


def export_protobuf(dir_name, worker_name=None):
    # protobuf dump contract kept as json-lines (no proto dep in-image)
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(filename):
    with open(filename) as f:
        return json.load(f)


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False, custom_device_types=None):
        self._scheduler = scheduler or _default_scheduler
        if isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self.targets = targets or [ProfilerTarget.CPU]
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._recording = False
        self._jax_trace_dir = None
        self._last_export_path = None
        self._summary = None
        self._events = []  # snapshot of the last recorded window
        self._drained = []  # events already pulled out of the tracer
        #                     mid-window (native ring drains destructively)
        self._window_begin_us = None  # record-window bounds for scoping
        self._window_end_us = None    # the merged metric counter events
        self._prev_op_tracer = None
        self._step_begin = None
        self._benchmark = Benchmark()

    # -- lifecycle -------------------------------------------------------
    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def start(self):
        global _active_profiler
        _active_profiler = self
        self._benchmark.begin()
        if self._timer_only:
            return
        self._state = self._scheduler(self._step)
        self._apply_state()

    def stop(self):
        global _active_profiler
        self._benchmark.end()
        if not self._timer_only:
            if self._recording:
                self._stop_recording(return_trace=True)
        _active_profiler = None

    def step(self, num_samples=None):
        """Advance the scheduler one training step."""
        self._benchmark.step(num_samples)
        if self._timer_only:
            self._step += 1
            return
        prev = self._state
        now = time.perf_counter()
        if (prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
                and self._step_begin is not None):
            _tracer.record(f"ProfileStep#{self._step}",
                           TracerEventType.ProfileStep,
                           self._step_begin * 1e6,
                           (now - self._step_begin) * 1e6,
                           threading.get_ident())
        self._step_begin = now
        self._step += 1
        self._state = self._scheduler(self._step)
        if prev is ProfilerState.RECORD_AND_RETURN or (
                self._recording
                and self._state in (ProfilerState.CLOSED, ProfilerState.READY)):
            self._stop_recording(return_trace=True)
        self._apply_state()

    def step_info(self, unit=None):
        return self._benchmark.step_info(unit)

    def _apply_state(self):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            if not self._recording:
                self._start_recording()

    def _start_recording(self):
        self._recording = True
        self._step_begin = time.perf_counter()
        self._window_begin_us = self._step_begin * 1e6
        self._window_end_us = None
        self._prev_op_tracer = _dispatch.set_op_tracer(_op_tracer_ctx)
        # device-activity leg (SURVEY §5.1: the reference consumes CUPTI
        # activity records via cuda_tracer.cc; on TPU the XLA/PJRT
        # profiler is that source). The captured xplane protos land in a
        # TensorBoard-loadable plugin dir exposed as `device_trace_dir`.
        if any(t is not ProfilerTarget.CPU for t in self.targets):
            import tempfile
            try:
                import jax
                self._jax_trace_dir = tempfile.mkdtemp(
                    prefix="paddle_tpu_xprof_")
                jax.profiler.start_trace(self._jax_trace_dir)
            except Exception:
                self._jax_trace_dir = None

    def _snapshot_window(self):
        """Everything recorded in the current window so far: what was
        already drained out of the tracer (a mid-window export/summary
        empties the native ring destructively) plus whatever the tracer
        still holds — snapshot once, reuse everywhere."""
        self._drained.extend(_tracer.drain())
        return list(self._drained)

    def _stop_recording(self, return_trace):
        self._recording = False
        self._window_end_us = time.perf_counter() * 1e6
        _dispatch.set_op_tracer(self._prev_op_tracer)
        self._prev_op_tracer = None
        if self._jax_trace_dir is not None:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                self._jax_trace_dir = None
        self._events = self._snapshot_window()  # keep so export() after
        self._summary = build_summary(self._events)  # stop still works
        self._drained = []
        _tracer.clear()
        if return_trace and self._on_trace_ready is not None:
            self._on_trace_ready(self)

    @property
    def device_trace_dir(self):
        """Directory holding the XLA profiler capture of the last recorded
        window (xplane protos; load in TensorBoard's profile plugin or
        with xprof tooling) — None when only CPU was targeted or capture
        failed."""
        return self._jax_trace_dir

    # -- export ----------------------------------------------------------
    def _export_chrome(self, path):
        source = self._snapshot_window() if self._recording \
            else self._events
        events = [{
            "name": name, "ph": "X", "cat": etype.name,
            "ts": ts, "dur": dur, "pid": os.getpid(), "tid": tid,
        } for name, etype, ts, dur, tid in source]
        # observability counter samples land in the SAME stream, so
        # serving gauges / compile counters plot against the host ranges
        # on one chrome://tracing timeline — scoped to THIS record
        # window (samples share the perf_counter timebase), not the
        # whole process-lifetime ring
        from ..observability import chrome_counter_events
        from ..observability.tracing import chrome_span_events
        until = None if self._recording else self._window_end_us
        events += chrome_counter_events(
            pid=os.getpid(), since_us=self._window_begin_us,
            until_us=until)
        # ... and so do the request-lifecycle spans: per-request lanes
        # (queue wait, prefill chunks, decode/spec spans, stalls) next
        # to the host ranges and metric counters — one view answers
        # "what was request N doing during the slow step"
        events += chrome_span_events(
            pid=os.getpid(), since_us=self._window_begin_us,
            until_us=until)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)

    def export(self, path, format="json"):
        self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        if self._summary is None:
            self._summary = build_summary(
                self._snapshot_window() if self._recording
                else self._events)
        print_summary(self._summary, time_unit=time_unit)
        return self._summary
