"""paddle.audio parity (reference: python/paddle/audio/ — functional
mel/dct utilities and feature Layers: Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC). Composed from paddle_tpu.signal.stft — the
whole pipeline is one XLA graph."""
from . import functional
from . import features
from . import backends
from . import datasets
from .backends import load, save, info

__all__ = ["functional", "features", "backends", "datasets", "load",
           "save", "info"]
