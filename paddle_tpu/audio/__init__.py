"""paddle.audio parity (reference: python/paddle/audio/ — functional
mel/dct utilities and feature Layers: Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC). Composed from paddle_tpu.signal.stft — the
whole pipeline is one XLA graph."""
from . import functional
from . import features

__all__ = ["functional", "features"]
