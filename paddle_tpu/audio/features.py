"""Audio feature layers (reference: python/paddle/audio/features/layers.py
— Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)."""
import jax.numpy as jnp

from ..nn.layer import Layer
from .. import signal as _signal
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self._n_fft = n_fft
        self._hop = hop_length or n_fft // 4
        self._win_length = win_length or n_fft
        self._window = F.get_window(window, self._win_length)
        self._power = power
        self._center = center
        self._pad_mode = pad_mode

    def forward(self, x):
        spec = _signal.stft(x, self._n_fft, self._hop, self._win_length,
                            window=self._window, center=self._center,
                            pad_mode=self._pad_mode)
        return (spec.abs() ** self._power).astype("float32")


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode)
        self._fbank = F.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        spec = self._spectrogram(x)          # [..., freq, time]
        from .. import ops
        return ops.matmul(self._fbank, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                   window, power, center, pad_mode, n_mels,
                                   f_min, f_max, htk, norm, dtype)
        self._ref, self._amin, self._top_db = ref_value, amin, top_db

    def forward(self, x):
        return F.power_to_db(self._mel(x), self._ref, self._amin,
                             self._top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                         window, power, center, pad_mode,
                                         n_mels, f_min, f_max, htk, norm,
                                         ref_value, amin, top_db, dtype)
        self._dct = F.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        from .. import ops
        logmel = self._logmel(x)             # [..., n_mels, time]
        return ops.matmul(self._dct.t(), logmel)
