"""Audio datasets (reference: python/paddle/audio/datasets/ — TESS, ESC50;
both download archives there). Zero-egress environment: datasets read a
local directory laid out like the reference archive; `mode='synthetic'`
generates deterministic waveforms so pipelines are testable offline."""
import os

import numpy as np

from ..io import Dataset
from . import features

__all__ = ["TESS", "ESC50"]


class _AudioClassifyDataset(Dataset):
    sample_rate = 16000
    duration = 1.0
    n_classes = 2

    def __init__(self, mode="train", feat_type="raw", data_dir=None,
                 archive=None, split=1, seed=0, n_samples=64, **feat_kwargs):
        self.mode = mode
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        self._files = []
        self._labels = []
        if data_dir and os.path.isdir(data_dir):
            self._index_local(data_dir)
        else:
            self._synthesize(seed, n_samples)

    def _index_local(self, data_dir):
        for root, _, files in os.walk(data_dir):
            for fn in sorted(files):
                if fn.endswith(".wav"):
                    self._files.append(os.path.join(root, fn))
                    self._labels.append(self._label_of(fn))

    def _label_of(self, filename):
        return 0

    def _synthesize(self, seed, n):
        rng = np.random.default_rng(seed)
        t = np.arange(int(self.sample_rate * self.duration)) / self.sample_rate
        self._waves = []
        for i in range(n):
            label = i % self.n_classes
            freq = 200.0 + 100.0 * label + rng.uniform(-10, 10)
            wav = 0.5 * np.sin(2 * np.pi * freq * t).astype(np.float32)
            self._waves.append(wav)
            self._labels.append(label)

    def _waveform(self, idx):
        if self._files:
            from .backends import load
            wav, _ = load(self._files[idx])
            return np.asarray(wav.numpy())[0]
        return self._waves[idx]

    def _extractor(self):
        # built once — the mel filterbank/DCT matrices and the compiled
        # STFT pipeline are shared by every sample
        if getattr(self, "_feat", None) is None:
            feat_cls = {"spectrogram": features.Spectrogram,
                        "melspectrogram": features.MelSpectrogram,
                        "logmelspectrogram": features.LogMelSpectrogram,
                        "mfcc": features.MFCC}[self.feat_type]
            kwargs = dict(self.feat_kwargs)
            if self.feat_type != "spectrogram":
                kwargs.setdefault("sr", self.sample_rate)
            self._feat = feat_cls(**kwargs)
        return self._feat

    def __getitem__(self, idx):
        wav = self._waveform(idx)
        label = self._labels[idx]
        if self.feat_type == "raw":
            return wav, label
        from ..core.tensor import Tensor
        x = Tensor(wav[None])
        return np.asarray(self._extractor()(x).numpy())[0], label

    def __len__(self):
        return len(self._labels)


class TESS(_AudioClassifyDataset):
    """Toronto Emotional Speech Set (reference audio/datasets/tess.py):
    7 emotion classes."""
    n_classes = 7
    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def _label_of(self, filename):
        for i, lab in enumerate(self.label_list):
            if lab in filename.lower():
                return i
        return 0


class ESC50(_AudioClassifyDataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py):
    50 classes, 5 folds."""
    n_classes = 50
    sample_rate = 44100
    duration = 0.25  # synthetic mode keeps tensors small

    def _label_of(self, filename):
        try:
            return int(os.path.splitext(filename)[0].split("-")[-1])
        except ValueError:
            return 0
