"""Audio functional utilities (reference: python/paddle/audio/functional/
functional.py — hz_to_mel/mel_to_hz/mel frequencies/fbank matrix/dct
matrix/windows)."""
import math

import numpy as np

from ..core.tensor import to_tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "create_dct", "power_to_db",
           "get_window"]


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    freq = np.asarray(freq, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(freq >= min_log_hz,
                    min_log_mel + np.log(np.maximum(freq, 1e-10)
                                         / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    mel = np.asarray(mel, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(mel >= min_log_mel,
                    min_log_hz * np.exp(logstep * (mel - min_log_mel)),
                    freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2] (reference
    compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return to_tensor(weights.astype(dtype))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (reference create_dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return to_tensor(dct.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10 with clamping (reference power_to_db)."""
    from .. import ops
    import jax.numpy as jnp
    from ..core.dispatch import apply_op

    def impl(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return apply_op("power_to_db", impl, (spect,), {})


def get_window(window, win_length, fftbins=True):
    """hann/hamming/blackman/bartlett windows (reference window_function).
    fftbins=True: periodic (denominator N — DFT-even, for STFT);
    fftbins=False: symmetric (denominator N-1, scipy semantics)."""
    t = np.arange(win_length)
    denom = float(win_length if fftbins else max(win_length - 1, 1))
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * t / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * t / denom)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * t / denom)
             + 0.08 * np.cos(4 * np.pi * t / denom))
    elif window == "bartlett":
        w = 1.0 - np.abs(2.0 * t / denom - 1.0)
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(win_length)
    else:
        raise ValueError(f"unsupported window: {window}")
    return to_tensor(w.astype("float32"))
