"""Audio IO backends (reference: python/paddle/audio/backends/ — wave_backend
default, soundfile optional). This environment has the stdlib `wave` module;
load/save/info cover PCM WAV, which is what the reference's default backend
supports (wave_backend.py)."""
import wave as _wave

import numpy as np

__all__ = ["list_available_backends", "get_current_backend", "set_backend",
           "load", "save", "info", "AudioInfo"]

_current = "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return _current


def set_backend(backend_name):
    global _current
    if backend_name not in list_available_backends():
        raise ValueError(f"backend {backend_name} unavailable; have "
                         f"{list_available_backends()}")
    _current = backend_name


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_frames = self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample})")


def info(filepath):
    """WAV header info (reference audio.info)."""
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Load PCM WAV -> (Tensor [C, T] float32 in [-1, 1], sample_rate)
    (reference audio.load)."""
    from ..core.tensor import Tensor
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n = f.getnframes()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(count)
    if width == 3:
        # 24-bit PCM: widen each 3-byte little-endian frame to int32
        b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 3)
        data = ((b[:, 0].astype(np.int32)) | (b[:, 1].astype(np.int32) << 8)
                | (b[:, 2].astype(np.int8).astype(np.int32) << 16))
        data = data.reshape(-1, ch)
    else:
        try:
            dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
        except KeyError:
            raise ValueError(f"unsupported WAV sample width {width} bytes")
        data = np.frombuffer(raw, dtype=dt).reshape(-1, ch)
    if normalize:
        if width == 1:
            wav = (data.astype(np.float32) - 128.0) / 128.0
        else:
            wav = data.astype(np.float32) / float(2 ** (8 * width - 1))
    else:
        # raw sample values; 8-bit WAV is unsigned so center it to keep the
        # zero point consistent across widths
        wav = data.astype(np.float32) - (128.0 if width == 1 else 0.0)
    out = wav.T if channels_first else wav
    return Tensor(np.ascontiguousarray(out)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    """Save float waveform to PCM WAV (reference audio.save)."""
    arr = np.asarray(src.numpy() if hasattr(src, "numpy") else src,
                     np.float32)
    if channels_first:
        arr = arr.T  # -> [T, C]
    if arr.ndim == 1:
        arr = arr[:, None]
    width = bits_per_sample // 8
    peak = float(2 ** (bits_per_sample - 1) - 1)
    data = np.clip(arr, -1.0, 1.0) * peak
    if width == 3:
        ints = data.astype(np.int32)
        frames = np.empty((ints.size, 3), np.uint8)
        flat = ints.reshape(-1)
        frames[:, 0] = flat & 0xFF
        frames[:, 1] = (flat >> 8) & 0xFF
        frames[:, 2] = (flat >> 16) & 0xFF
        payload = frames.tobytes()
    else:
        try:
            dt = {2: np.int16, 4: np.int32}[width]
        except KeyError:
            raise ValueError(
                f"unsupported bits_per_sample {bits_per_sample}")
        payload = data.astype(dt).tobytes()
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(width)
        f.setframerate(int(sample_rate))
        f.writeframes(payload)
