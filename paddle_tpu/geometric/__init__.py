"""paddle.geometric parity (reference: python/paddle/geometric/ — graph
message passing send_u_recv/send_ue_recv/send_uv, segment reductions,
neighbor sampling; kernels paddle/phi/kernels/gpu/graph_*.cu).

TPU lowering: message passing is gather + segment reduction — XLA-native,
static shapes (edge lists are fixed-size arrays)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "sample_neighbors", "reindex_graph",
]

_REDUCES = {"sum", "mean", "max", "min"}


def _segment(values, ids, n, pool):
    if pool == "sum":
        return jax.ops.segment_sum(values, ids, num_segments=n)
    if pool == "mean":
        s = jax.ops.segment_sum(values, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones(ids.shape, values.dtype), ids,
                                num_segments=n)
        return s / jnp.maximum(c, 1.0).reshape(
            (-1,) + (1,) * (values.ndim - 1))
    if pool == "max":
        out = jax.ops.segment_max(values, ids, num_segments=n)
        return jnp.where(jnp.isfinite(out), out, 0.0)
    out = jax.ops.segment_min(values, ids, num_segments=n)
    return jnp.where(jnp.isfinite(out), out, 0.0)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """Gather x at src, reduce onto dst (reference geometric/message_passing
    send_u_recv)."""
    if reduce_op not in _REDUCES:
        raise ValueError(f"reduce_op must be one of {_REDUCES}")
    n = out_size

    def impl(xa, src, dst):
        m = n if n is not None else xa.shape[0]
        return _segment(jnp.take(xa, src, axis=0), dst, m, reduce_op)

    return apply_op("graph_send_u_recv", impl, (x, src_index, dst_index),
                    {})


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    """Node+edge message passing (send_ue_recv): combine gathered node
    features with edge features then reduce."""
    comb = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}[message_op]
    n = out_size

    def impl(xa, ya, src, dst):
        m = n if n is not None else xa.shape[0]
        msg = comb(jnp.take(xa, src, axis=0), ya)
        return _segment(msg, dst, m, reduce_op)

    return apply_op("graph_send_ue_recv", impl,
                    (x, y, src_index, dst_index), {})


def send_uv(x, y, src_index, dst_index, message_op="add"):
    """Edge-wise message from both endpoints (send_uv): no reduction."""
    comb = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}[message_op]

    def impl(xa, ya, src, dst):
        return comb(jnp.take(xa, src, axis=0), jnp.take(ya, dst, axis=0))

    return apply_op("graph_send_uv", impl, (x, y, src_index, dst_index), {})


def _make_segment(name):
    def op(data, segment_ids, num_segments=None):
        def impl(d, ids):
            if num_segments is not None:
                n = int(num_segments)
            elif isinstance(ids, jax.core.Tracer):
                # XLA needs a static segment count; max(ids)+1 is
                # data-dependent, so tracing requires it explicitly
                raise ValueError(
                    f"segment_{name} under jit/to_static needs "
                    "num_segments= (static shapes); eager mode infers it")
            else:
                n = int(jnp.max(ids)) + 1
            return _segment(d, ids, n, name)
        return apply_op(f"segment_{name}", impl, (data, segment_ids), {})
    op.__name__ = f"segment_{name}"
    return op


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_max = _make_segment("max")
segment_min = _make_segment("min")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None):
    """Uniform neighbor sampling from a CSC graph (reference
    geometric/sampling/neighbors.py). Host-side structure op (sampling is
    data-dependent — the eager boundary, like sparse structure ops)."""
    row_np = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    colptr_np = np.asarray(colptr.numpy() if isinstance(colptr, Tensor)
                           else colptr)
    nodes = np.asarray(input_nodes.numpy()
                       if isinstance(input_nodes, Tensor) else input_nodes)
    eids_np = None
    if eids is not None:
        eids_np = np.asarray(eids.numpy() if isinstance(eids, Tensor)
                             else eids)
    rng = np.random.default_rng()
    out_neighbors, out_counts, out_eids = [], [], []
    for nid in nodes.reshape(-1):
        lo, hi = int(colptr_np[nid]), int(colptr_np[nid + 1])
        sel = np.arange(lo, hi)
        if sample_size > 0 and len(sel) > sample_size:
            sel = rng.choice(sel, sample_size, replace=False)
        out_neighbors.append(row_np[sel])
        out_counts.append(len(sel))
        if return_eids:
            out_eids.append(eids_np[sel] if eids_np is not None else sel)
    from ..core.tensor import to_tensor
    nbr = to_tensor(np.concatenate(out_neighbors).astype(np.int64)
                    if out_neighbors else np.zeros(0, np.int64))
    cnt = to_tensor(np.asarray(out_counts, np.int64))
    if return_eids:
        e = to_tensor(np.concatenate(out_eids).astype(np.int64)
                      if out_eids else np.zeros(0, np.int64))
        return nbr, cnt, e
    return nbr, cnt


def reindex_graph(x, neighbors, count):
    """Compact node ids (reference geometric/reindex.py): maps x ++ unique
    new neighbors to [0, n)."""
    x_np = np.asarray(x.numpy() if isinstance(x, Tensor) else x).reshape(-1)
    nb = np.asarray(neighbors.numpy() if isinstance(neighbors, Tensor)
                    else neighbors).reshape(-1)
    ids = {int(v): i for i, v in enumerate(x_np)}
    order = list(x_np)
    for v in nb:
        if int(v) not in ids:
            ids[int(v)] = len(order)
            order.append(v)
    from ..core.tensor import to_tensor
    reindexed = np.asarray([ids[int(v)] for v in nb], np.int64)
    return (to_tensor(reindexed),
            to_tensor(np.asarray(order, np.int64)),
            to_tensor(np.asarray(np.arange(len(x_np)), np.int64)))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False):
    """Weighted neighbor sampling (reference
    geometric/sampling/neighbors.py weighted variant): neighbors drawn
    without replacement, probability proportional to edge weight."""
    row_np = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    col_np = np.asarray(colptr.numpy() if isinstance(colptr, Tensor) else colptr)
    w_np = np.asarray(edge_weight.numpy() if isinstance(edge_weight, Tensor)
                      else edge_weight).astype(np.float64)
    nodes = np.asarray(input_nodes.numpy() if isinstance(input_nodes, Tensor)
                       else input_nodes).reshape(-1)
    eid_np = np.asarray(eids.numpy() if isinstance(eids, Tensor) else eids) \
        if eids is not None else None
    rng = np.random.default_rng()
    out_nbr, out_cnt, out_eids = [], [], []
    for n in nodes:
        lo, hi = int(col_np[n]), int(col_np[n + 1])
        cand = row_np[lo:hi]
        wts = w_np[lo:hi]
        k = len(cand) if sample_size < 0 else min(sample_size, len(cand))
        if len(cand) == 0 or k == 0:
            out_cnt.append(0)
            continue
        if wts.sum() > 0:
            p = wts / wts.sum()
            # without replacement k is capped by the number of non-zero
            # weight neighbors (choice raises otherwise)
            k = min(k, int((wts > 0).sum()))
        else:
            p = None
        sel = rng.choice(len(cand), size=k, replace=False, p=p)
        out_nbr.append(cand[sel])
        out_cnt.append(k)
        if eid_np is not None:
            out_eids.append(eid_np[lo:hi][sel])
    nbrs = np.concatenate(out_nbr) if out_nbr else np.zeros((0,), row_np.dtype)
    res = (Tensor(nbrs), Tensor(np.asarray(out_cnt, np.int32)))
    if return_eids and eid_np is not None:
        res = res + (Tensor(np.concatenate(out_eids) if out_eids
                            else np.zeros((0,), eid_np.dtype)),)
    return res


def reindex_heter_graph(x, neighbors, count):
    """reindex_graph over per-edge-type neighbor lists (reference
    reindex_heter_graph): one shared node numbering, per-type edges."""
    x_np = np.asarray(x.numpy() if isinstance(x, Tensor) else x).reshape(-1)
    nbr_list = [np.asarray(n.numpy() if isinstance(n, Tensor) else n).reshape(-1)
                for n in neighbors]
    cat = np.concatenate([x_np] + nbr_list)
    # paddle semantics: ids numbered by first appearance (x first)
    first_idx = {v: i for i, v in enumerate(dict.fromkeys(cat.tolist()))}
    remap = np.asarray([first_idx[v] for v in cat.tolist()], np.int64)
    off = len(x_np)
    outs = []
    for n in nbr_list:
        outs.append(Tensor(remap[off:off + len(n)]))
        off += len(n)
    order = np.asarray(list(dict.fromkeys(cat.tolist())), x_np.dtype)
    return outs, Tensor(order)
