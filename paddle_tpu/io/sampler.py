"""Samplers (reference: python/paddle/io/dataloader/sampler.py,
batch_sampler.py incl. DistributedBatchSampler)."""
import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        self._rng = np.random.default_rng()

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None \
            else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(self._rng.integers(0, n, self.num_samples).tolist())
        return iter(self._rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__(None)
        self.indices = list(indices)
        self._rng = np.random.default_rng()

    def __iter__(self):
        return iter([self.indices[i]
                     for i in self._rng.permutation(len(self.indices))])

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray([float(w) for w in weights])
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.default_rng().choice(
            len(self.weights), size=self.num_samples,
            replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the sample space across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler).
    On the TPU stack ranks come from the mesh's data axis."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / num_replicas))
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            indices = np.random.default_rng(self.epoch).permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]  # pad to even shards
        local = indices[self.local_rank::self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size
