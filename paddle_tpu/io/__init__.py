"""paddle.io surface (reference: python/paddle/io/)."""
from .dataset import (Dataset, IterableDataset, TensorDataset, ConcatDataset,
                      ChainDataset, Subset, random_split, ComposeDataset,
                      get_worker_info)
from .sampler import (Sampler, SequenceSampler, RandomSampler,
                      SubsetRandomSampler, WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler)
from .dataloader import DataLoader, default_collate_fn
