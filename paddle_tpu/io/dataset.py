"""Datasets (reference: python/paddle/io/dataloader/dataset.py)."""
import bisect


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import numpy as np
    from ..core import random as _random
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        counts = [int(np.floor(n * f)) for f in lengths]
        counts[0] += n - sum(counts)
        lengths = counts
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    import jax
    key = generator if generator is not None else _random.next_key()
    perm = np.asarray(jax.random.permutation(key, len(dataset)))
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


class ComposeDataset(Dataset):
    """Zip map-style datasets: sample i concatenates the fields of each
    dataset's sample i (reference ComposeDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "ComposeDataset needs at least one dataset"
        self._len = min(len(d) for d in self.datasets)

    def __len__(self):
        return self._len

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(sample)


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    """Inside a DataLoader worker: (id, num_workers, dataset); None in the
    main process (reference get_worker_info)."""
    return _worker_info
