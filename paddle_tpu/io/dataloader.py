"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py:154,368
— multiprocess workers + shared memory + prefetch).

TPU-native design: the loader's job is to keep the host→HBM pipe full while
the device computes. num_workers>0 uses a background-thread prefetch queue
(numpy collation releases the GIL for the heavy copies); batches are collated
to numpy and converted to device tensors at yield time, so a jit'd train step
overlaps H2D with compute via jax's async dispatch.
"""
import collections.abc
import pickle
import queue
import threading

import numpy as np


def _np_collate(batch):
    """Worker-side collate: numpy-only (workers never import jax; the main
    process converts ndarrays to device tensors at yield time)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, collections.abc.Mapping):
        return {k: _np_collate([s[k] for s in batch]) for k in sample}
    if isinstance(sample, collections.abc.Sequence):
        return [_np_collate(list(col)) for col in zip(*batch)]
    raise TypeError(f"cannot collate {type(sample)}")


class _SpawnUnavailable(Exception):
    pass


_SHM_MIN_BYTES = 1 << 16  # below this, queue pickling is cheaper than shm


def _to_shm(tree):
    """Move large ndarrays of a collated batch into POSIX shared memory
    (reference: the worker-side shared-memory transport of
    io/dataloader/worker.py): the queue then carries only
    (name, dtype, shape) stubs instead of pickled buffers."""
    from multiprocessing import shared_memory
    if isinstance(tree, np.ndarray) and tree.nbytes >= _SHM_MIN_BYTES:
        shm = shared_memory.SharedMemory(create=True, size=tree.nbytes)
        np.ndarray(tree.shape, tree.dtype, buffer=shm.buf)[...] = tree
        name = shm.name
        shm.close()
        return ("__shm__", name, str(tree.dtype), tree.shape)
    if isinstance(tree, dict):
        return {k: _to_shm(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_to_shm(v) for v in tree]
    return tree


def _from_shm(tree):
    """Main-process side: attach, copy out, unlink."""
    from multiprocessing import shared_memory
    if isinstance(tree, tuple) and len(tree) == 4 and tree[0] == "__shm__":
        _, name, dtype, shape = tree
        shm = shared_memory.SharedMemory(name=name)
        try:
            out = np.ndarray(shape, dtype, buffer=shm.buf).copy()
        finally:
            shm.close()
            shm.unlink()
        return out
    if isinstance(tree, dict):
        return {k: _from_shm(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_from_shm(v) for v in tree]
    return tree


_RING_CAPACITY = 32 << 20


def _ring_bytes(tree):
    """Total shm-eligible payload of a batch."""
    if isinstance(tree, np.ndarray) and tree.nbytes >= _SHM_MIN_BYTES:
        return tree.nbytes
    if isinstance(tree, dict):
        return sum(_ring_bytes(v) for v in tree.values())
    if isinstance(tree, list):
        return sum(_ring_bytes(v) for v in tree)
    return 0


def _to_ring(tree, ring, count):
    """Serialize large ndarrays into the worker's native shm ring
    (native/src/shm_ring.cc — the fixed mapped-once transport replacing a
    per-batch SharedMemory segment; reference data_loader.cc role).
    `count` is a 1-item list tracking pushed records, so an error mid-batch
    can tell the consumer exactly how many orphans to drain."""
    if isinstance(tree, np.ndarray) and tree.nbytes >= _SHM_MIN_BYTES:
        # generous timeout: the consumer drains at queue-receipt, which
        # can lag by prefetch depth under load — blocking here is normal
        ring.push(np.ascontiguousarray(tree).tobytes(), timeout_ms=60_000)
        count[0] += 1
        return ("__ring__", str(tree.dtype), tree.shape)
    if isinstance(tree, dict):
        return {k: _to_ring(v, ring, count) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_to_ring(v, ring, count) for v in tree]
    return tree


def _from_ring(tree, ring):
    """Main-process side: pop records in push order (per-worker FIFO)."""
    if isinstance(tree, tuple) and len(tree) == 3 and tree[0] == "__ring__":
        _, dtype, shape = tree
        buf = ring.pop(timeout_ms=60_000)
        if buf is None:
            raise RuntimeError("DataLoader ring transport timed out")
        return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    if isinstance(tree, dict):
        return {k: _from_ring(v, ring) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_from_ring(v, ring) for v in tree]
    return tree


def _worker_loop(dataset, index_queue, data_queue, collate, init_fn, wid,
                 use_shm=False, ring_name=None):
    """Process-worker loop (reference: io/dataloader/worker.py — fetch
    sample indices, collate, ship the batch back over the queue, through
    per-batch shared memory, or through the native shm ring)."""
    from . import dataset as _ds
    _ds._worker_info = _ds._WorkerInfo(wid, -1, dataset)
    if init_fn is not None:
        init_fn(wid)
    ring = None
    if ring_name is not None:
        try:
            from ..native import ShmRing
            ring = ShmRing.attach(ring_name)
        except Exception:
            ring = None
    while True:
        item = index_queue.get()
        if item is None:
            return
        seq, indices = item
        pushed = [0]
        try:
            batch = collate([dataset[i] for i in indices])
            # batches too big for the ring (whole batch > half the ring,
            # or any single record near capacity) go through per-batch
            # SharedMemory segments — same stubs, the consumer handles
            # both kinds in one materialize pass
            if ring is not None and                     _ring_bytes(batch) <= _RING_CAPACITY // 2:
                batch = _to_ring(batch, ring, pushed)
            elif use_shm:
                batch = _to_shm(batch)
            data_queue.put((seq, batch, None))
        except Exception as e:  # graftlint: disable=GL113 - the exception rides the resync stub to the consumer, which re-raises it
            # resync stub: the consumer drains exactly the records this
            # batch managed to push before failing (keeps the per-worker
            # FIFO aligned for persistent pools)
            data_queue.put((seq, ("__ring_drain__", pushed[0]), e))

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, collections.abc.Mapping):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, collections.abc.Sequence):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(col)) for col in transposed]
    raise TypeError(f"cannot collate {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=False,
                 instrument=False):
        self.dataset = dataset
        # instrument=True wraps iteration with the training-health
        # data-pipeline telemetry (observability/train_health.py:
        # per-batch wait histogram + `data_wait` chrome spans,
        # queue-depth gauge, stall detector). Off by default: the
        # loader stays importable/usable without the observability
        # stack in the loop.
        self.instrument = bool(instrument)
        self.health_monitor = None      # TrainHealthMonitor, optional
        self._live_queue = None         # thread-prefetch queue, live
        self.collate_fn = collate_fn or default_collate_fn
        self._custom_collate = collate_fn is not None
        self.num_workers = num_workers
        self.worker_init_fn = worker_init_fn
        self.use_process_workers = use_process_workers
        self.use_shared_memory = use_shared_memory
        self.persistent_workers = persistent_workers
        self.timeout = timeout
        self.prefetch_factor = max(prefetch_factor, 1)
        self._handles = None  # live worker pool when persistent_workers
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _batches(self):
        if self._iterable:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    @staticmethod
    def _to_tensor_tree(b):
        if isinstance(b, np.ndarray):
            return Tensor(b)
        if isinstance(b, dict):
            return {k: DataLoader._to_tensor_tree(v) for k, v in b.items()}
        if isinstance(b, list) and b and isinstance(
                b[0], (np.ndarray, dict, list)):
            return [DataLoader._to_tensor_tree(v) for v in b]
        return b

    def _start_process_workers(self):
        """Spawn the worker pool; raises _SpawnUnavailable only during
        startup (unpicklable dataset), so the thread fallback can never
        replay batches that process workers already yielded.

        NOTE (spawn contract, same as the reference's/PyTorch's): the
        launching script must be import-safe (`if __name__ == "__main__"`),
        and a custom collate_fn runs IN the worker and must return
        picklable numpy/python data (workers never touch jax)."""
        import multiprocessing as mp
        ctx = mp.get_context("spawn")  # fork after jax init is unsafe
        collate = self.collate_fn if self._custom_collate else _np_collate
        index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        data_queue = ctx.Queue()
        # native ring transport: one mapped-once SPSC ring per worker
        # (falls back to per-batch SharedMemory segments when the native
        # lib is unavailable)
        self._rings = None
        ring_names = [None] * self.num_workers
        if self.use_shared_memory:
            created = []
            try:
                import os as _os
                from ..native import ShmRing
                names = [f"/pt_dl_{_os.getpid()}_{id(self) & 0xffffff}_{w}"
                         for w in range(self.num_workers)]
                for nm in names:
                    created.append(ShmRing.create(nm, _RING_CAPACITY))
                self._rings = created
                ring_names = names
            except Exception:
                for r in created:   # partial failure must not leak shm
                    try:
                        r.close()
                        r.free()
                    except Exception:  # graftlint: disable=GL113 - best-effort shm cleanup on an already-failing path; the OUTER handler records the fallback
                        pass
                self._rings = None
        procs = [ctx.Process(
            target=_worker_loop,
            args=(self.dataset, index_queues[w], data_queue, collate,
                  self.worker_init_fn, w, self.use_shared_memory,
                  ring_names[w]),
            daemon=True)
            for w in range(self.num_workers)]
        try:
            for p in procs:
                p.start()
        except (RuntimeError, TypeError, AttributeError, OSError,
                ImportError, pickle.PickleError) as e:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            raise _SpawnUnavailable(str(e))
        return procs, index_queues, data_queue

    def _queue_get(self, data_queue, procs):
        """Liveness-checked read: a dead worker raises instead of hanging
        the trainer forever; self.timeout (when > 0) bounds the total wait
        per batch (reference DataLoader timeout semantics)."""
        import time
        deadline = (time.monotonic() + self.timeout) if self.timeout else None
        while True:
            try:
                return data_queue.get(timeout=5)
            except queue.Empty:
                dead = [p for p in procs if not p.is_alive()]
                if dead:
                    raise RuntimeError(
                        f"DataLoader worker (pid {dead[0].pid}) died "
                        f"unexpectedly (exit {dead[0].exitcode})")
                if deadline is not None and time.monotonic() > deadline:
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s")

    def _iter_process_workers(self, procs, index_queues, data_queue):
        """True multiprocess workers (reference dataloader_iter.py:368).
        Batch order is preserved with a sequence-number reorder buffer;
        `prefetch_factor` bounds in-flight batches per worker. With
        persistent_workers the pool idles on its index queues between
        epochs instead of being torn down (reference persistent_workers)."""
        received = 0
        sent = 0
        try:
            batches = list(self.batch_sampler)
            n = len(batches)
            inflight_cap = self.num_workers * self.prefetch_factor
            done = {}
            next_out = 0
            while sent < min(inflight_cap, n):
                index_queues[sent % self.num_workers].put(
                    (sent, batches[sent]))
                sent += 1
            while next_out < n:
                while next_out not in done:
                    seq, batch, err = self._queue_get(data_queue, procs)
                    received += 1
                    if err is not None:
                        self._drain_ring_orphans(seq, batch)
                        raise err
                    if self._rings is not None and batch is not None:
                        # seq was dealt round-robin: worker = seq % W
                        batch = _from_ring(
                            batch, self._rings[seq % self.num_workers])
                    if self.use_shared_memory and batch is not None:
                        batch = _from_shm(batch)  # whole-batch fallback
                    done[seq] = batch
                    if sent < n:
                        index_queues[sent % self.num_workers].put(
                            (sent, batches[sent]))
                        sent += 1
                b = done.pop(next_out)
                next_out += 1
                yield (self._to_tensor_tree(b) if not self._custom_collate
                       else b)
        finally:
            if not self.persistent_workers:
                self._shutdown_pool(procs, index_queues)
                self._free_rings()
            else:
                # abandoned-epoch drain: in-flight results must not leak
                # into the NEXT epoch's reorder buffer (seq restarts at 0),
                # and their shm segments must be unlinked
                while received < sent:
                    try:
                        sseq, stale, _err = self._queue_get(data_queue,
                                                            procs)
                    except Exception:  # graftlint: disable=GL113 - bounded abandoned-epoch drain: break exits, a dead worker just ends the drain early
                        break
                    received += 1
                    if stale is not None and self.use_shared_memory:
                        try:
                            if isinstance(stale, tuple) and stale and \
                                    stale[0] == "__ring_drain__":
                                self._drain_ring_orphans(sseq, stale)
                            elif self._rings is not None:
                                _from_ring(stale, self._rings[
                                    sseq % self.num_workers])
                                _from_shm(stale)
                            else:
                                _from_shm(stale)  # attach + unlink
                        except Exception:  # graftlint: disable=GL113 - best-effort shm unlink of ABANDONED results during teardown; nothing downstream consumes them
                            pass

    @staticmethod
    def _shutdown_pool(procs, index_queues):
        for iq in index_queues:
            try:
                iq.put_nowait(None)
            except Exception:
                pass
        for p in procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        # never block interpreter exit on queue feeder threads: a worker
        # terminated mid-write can leave the pipe lock held, and the
        # default Queue.__del__ join would hang the process at shutdown
        for iq in index_queues:
            try:
                iq.cancel_join_thread()
                iq.close()
            except Exception:
                pass

    def _drain_ring_orphans(self, seq, stub):
        """Pop records a failed batch left in its worker's ring (the
        worker reports how many via the __ring_drain__ stub)."""
        if (self._rings is None or not isinstance(stub, tuple) or not stub
                or stub[0] != "__ring_drain__"):
            return
        ring = self._rings[seq % self.num_workers]
        for _ in range(int(stub[1])):
            try:
                ring.pop(timeout_ms=1000)
            except Exception:  # graftlint: disable=GL113 - bounded orphan drain: break exits; the worker's error already rode the resync stub
                break

    def _free_rings(self):
        rings = getattr(self, "_rings", None)
        if rings:
            for r in rings:
                try:
                    r.close()
                    r.free()
                except Exception:
                    pass
        self._rings = None

    def __del__(self):
        if getattr(self, "_handles", None) is not None:
            procs, index_queues, _ = self._handles
            try:
                self._shutdown_pool(procs, index_queues)
            except Exception:
                pass
        try:
            self._free_rings()
        except Exception:
            pass

    def __iter__(self):
        if self.instrument:
            # lazy import: the observability stack only loads when the
            # caller opted into telemetry
            from ..observability import train_health as _th
            return _th.instrument_loader(
                self._iter_impl(), monitor=self.health_monitor,
                queue_depth=self._queue_depth)
        return self._iter_impl()

    def _queue_depth(self):
        q = self._live_queue
        return q.qsize() if q is not None else 0

    def _iter_impl(self):
        if self.num_workers == 0:
            yield from self._batches()
            return
        if (self.use_process_workers and not self._iterable
                and self.num_workers > 0):
            handles = self._handles
            if handles is not None and any(not p.is_alive()
                                           for p in handles[0]):
                # a worker died between epochs: retire the WHOLE old pool
                # before replacing it (surviving workers must not leak)
                self._shutdown_pool(handles[0], handles[1])
                self._handles = handles = None
            if handles is None:
                try:
                    handles = self._start_process_workers()
                except _SpawnUnavailable:
                    handles = None  # unpicklable dataset: thread fallback
            if handles is not None:
                if self.persistent_workers:
                    self._handles = handles
                # startup succeeded: from here errors propagate (no replay)
                yield from self._iter_process_workers(*handles)
                return
        # background-thread prefetch (role of the reference's worker pool +
        # shared-memory queue, dataloader_iter.py:368)
        q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()
        err = []
        closed = threading.Event()

        def producer():
            try:
                for b in self._batches():
                    while not closed.is_set():
                        try:
                            q.put(b, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if closed.is_set():
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                # the sentinel gets the SAME closed-flag retry loop as
                # data puts: a put_nowait here dropped it whenever the
                # consumer was merely SLOW (queue still full at epoch
                # end), leaving the consumer blocked on q.get() forever
                # — exposed by the instrumented-loader stall test, which
                # slows the consumer by a histogram observe per batch
                while not closed.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        self._live_queue = q
        try:
            while True:
                b = q.get()
                if b is sentinel:
                    break
                yield b
        finally:
            # consumer abandoned mid-epoch (break in a training loop):
            # unblock and retire the producer instead of leaking it
            self._live_queue = None
            closed.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5)
        if err:
            raise err[0]
