"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py:154,368
— multiprocess workers + shared memory + prefetch).

TPU-native design: the loader's job is to keep the host→HBM pipe full while
the device computes. num_workers>0 uses a background-thread prefetch queue
(numpy collation releases the GIL for the heavy copies); batches are collated
to numpy and converted to device tensors at yield time, so a jit'd train step
overlaps H2D with compute via jax's async dispatch.
"""
import collections.abc
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, collections.abc.Mapping):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, collections.abc.Sequence):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(col)) for col in transposed]
    raise TypeError(f"cannot collate {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _batches(self):
        if self._iterable:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._batches()
            return
        # background-thread prefetch (role of the reference's worker pool +
        # shared-memory queue, dataloader_iter.py:368)
        q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()
        err = []
        closed = threading.Event()

        def producer():
            try:
                for b in self._batches():
                    while not closed.is_set():
                        try:
                            q.put(b, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if closed.is_set():
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                try:
                    q.put_nowait(sentinel)
                except queue.Full:
                    pass  # consumer is gone; closed flag ends the thread

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                b = q.get()
                if b is sentinel:
                    break
                yield b
        finally:
            # consumer abandoned mid-epoch (break in a training loop):
            # unblock and retire the producer instead of leaking it
            closed.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5)
        if err:
            raise err[0]
