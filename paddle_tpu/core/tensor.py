"""Eager Tensor.

The reference's user tensor is paddle::Tensor (paddle/phi/api/include/tensor.h:82)
over DenseTensor (paddle/phi/core/dense_tensor.h:37) with AutogradMeta
(paddle/fluid/eager/autograd_meta.h:61) bolted on. Here the storage *is* a
jax.Array (a PJRT buffer on TPU — device memory, sharding, and layout are
owned by the runtime), and the autograd meta is three slots: `_node`,
`_out_idx`, `stop_gradient`.

Semantics follow paddle: tensors default to stop_gradient=True; Parameters
default to stop_gradient=False; `.backward()` seeds the tape walk.
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import autograd as ag
from .dtypes import convert_dtype
from .dispatch import apply_op


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_idx",
                 "_hooks", "_retain_grad", "name", "persistable",
                 "_trainable", "_dist_meta", "__weakref__", "__dict__")

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            dt = convert_dtype(dtype)
            if dt is None and isinstance(data, (bool, int, float, list, tuple)):
                # paddle default dtypes: python floats -> float32, ints -> int64
                # (jax x64 is off, so int64 canonicalizes to int32 — TPU-friendly)
                arr = np.asarray(data)
                if arr.dtype == np.float64:
                    dt = np.float32
            data = jnp.asarray(data, dtype=dt)
        elif dtype is not None:
            data = data.astype(convert_dtype(dtype))
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self._hooks = []
        self._retain_grad = False
        self.name = name
        self.persistable = False
        self._trainable = None  # None: follow (not stop_gradient)

    @property
    def trainable(self):
        # tracks stop_gradient unless explicitly set (Parameter sets it);
        # keeps late `t.stop_gradient = False` visible to optimizers
        if self._trainable is None:
            return not self.stop_gradient
        return self._trainable

    @trainable.setter
    def trainable(self, v):
        self._trainable = bool(v)

    # -- storage --------------------------------------------------------
    @property
    def data(self):
        return self._data

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else value

    @property
    def shape(self):
        # Partial-placement DTensors store hidden leading stack dims (see
        # paddle_tpu/distributed/dtensor.py); logical shape excludes them
        meta = getattr(self, "_dist_meta", None)
        if meta is not None and meta.partial_axes:
            return list(self._data.shape[len(meta.partial_axes):])
        return list(self._data.shape)

    @property
    def ndim(self):
        meta = getattr(self, "_dist_meta", None)
        if meta is not None and meta.partial_axes:
            return self._data.ndim - len(meta.partial_axes)
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        from .device import Place
        try:
            dev = list(self._data.devices())[0]
            return Place(dev.platform, dev.id)
        except Exception:
            return Place("traced", 0)

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from .. import ops
        return ops.t(self)

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    def element_size(self):
        return self._data.dtype.itemsize

    def is_contiguous(self):
        return True

    def contiguous(self):
        return self

    # -- host interop ---------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return self._data.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    # -- autograd -------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False,
                 create_graph=False):
        ag.backward(self, grad_tensors=None if grad_tensor is None else [grad_tensor],
                    retain_graph=retain_graph, create_graph=create_graph)

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self._node = None
        self._out_idx = 0
        self.stop_gradient = True
        return self

    def clone(self):
        return apply_op("clone", jnp.copy, (self,), {})

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(handle_self):
                if hook in self._hooks:
                    self._hooks.remove(hook)
        return _Handle()

    def retain_grads(self):
        self._retain_grad = True

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data), stop_gradient=True)
        else:
            self.grad = None

    def _deposit_grad(self, g):
        from .selected_rows import SelectedRows
        if getattr(g, "dtype", None) == jax.dtypes.float0:
            return
        if isinstance(g, SelectedRows):
            # sparse embedding gradient: .grad IS the SelectedRows
            # (reference semantics; optimizers row-scatter it)
            self.grad = g if self.grad is None else self.grad + g
            return
        if isinstance(g, Tensor):
            # create_graph path: keep the grad's tape node so the deposited
            # .grad supports another backward (gradient-penalty training)
            self.grad = g if self.grad is None else self.grad + g
            return
        if self.grad is None:
            self.grad = Tensor(g, stop_gradient=True)
        else:
            self.grad = Tensor(self.grad._data + g, stop_gradient=True)

    def _wrap_grad(self, g):
        from .selected_rows import SelectedRows
        if isinstance(g, (Tensor, SelectedRows)):
            return g
        return Tensor(g, stop_gradient=True)

    # -- dtype / device -------------------------------------------------
    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    cast = astype

    def cpu(self):
        cpu_dev = jax.devices("cpu")[0]
        return Tensor(jax.device_put(self._data, cpu_dev), stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu", "xpu", "npu"):
                continue  # placement (incl. 'tpu:0' forms) is runtime-managed
            dtype = a
        return self.astype(dtype) if dtype is not None else self

    def pin_memory(self):
        return self

    # -- mutation -------------------------------------------------------
    def set_value(self, value):
        value = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        self._data = value.astype(self._data.dtype)
        return self

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    # -- random in-place fills (reference: paddle.Tensor.uniform_/normal_/
    # bernoulli_/cauchy_/geometric_/log_normal_/exponential_) -----------
    def _fill_random(self, sampler, seed=0):
        from . import random as _rng
        key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
        self._data = sampler(key).astype(self._data.dtype)
        return self

    def uniform_(self, min=-1.0, max=1.0, seed=0):
        return self._fill_random(lambda k: jax.random.uniform(
            k, self._data.shape, jnp.float32, min, max), seed=seed)

    def normal_(self, mean=0.0, std=1.0):
        return self._fill_random(lambda k: jax.random.normal(
            k, self._data.shape) * std + mean)

    def log_normal_(self, mean=1.0, std=2.0):
        return self._fill_random(lambda k: jnp.exp(jax.random.normal(
            k, self._data.shape) * std + mean))

    def bernoulli_(self, p=0.5):
        p = p._data if isinstance(p, Tensor) else p
        return self._fill_random(lambda k: jax.random.bernoulli(
            k, p, self._data.shape))

    def cauchy_(self, loc=0, scale=1):
        return self._fill_random(lambda k: loc + scale * jax.random.cauchy(
            k, self._data.shape))

    def geometric_(self, probs):
        probs = probs._data if isinstance(probs, Tensor) else probs
        return self._fill_random(lambda k: jax.random.geometric(
            k, probs, self._data.shape))

    def exponential_(self, lam=1.0):
        return self._fill_random(lambda k: jax.random.exponential(
            k, self._data.shape) / lam)

    def tolist(self):
        return self._data.tolist()

    def is_floating_point(self):
        return jnp.issubdtype(self._data.dtype, jnp.floating)

    def is_complex(self):
        return jnp.issubdtype(self._data.dtype, jnp.complexfloating)

    def is_integer(self):
        return jnp.issubdtype(self._data.dtype, jnp.integer)

    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply_op("getitem", lambda x: x[idx], (self,), {})

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)

        def impl(x, v):
            v = jnp.asarray(v, dtype=x.dtype) if not hasattr(v, "dtype") else v.astype(x.dtype)
            return x.at[idx].set(v)
        out = apply_op("setitem", impl, (self, value), {})
        # the tensor becomes the op's output in-place (autograd-correct
        # inplace write, same role as the reference's inplace version
        # counter on TensorWrapper)
        self._data = out._data
        self._node = out._node
        self._out_idx = out._out_idx
        self.stop_gradient = out.stop_gradient

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self._data)

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __index__(self):
        return int(self._data)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return str(self)

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {np.asarray(self._data)})")

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached by ops.registry at import time so the
    # whole operator surface stays YAML-driven; see paddle_tpu/ops/registry.py
    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a


def _unwrap_index(idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._data
        return i
    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


class Parameter(Tensor):
    """Trainable leaf tensor (reference: paddle Parameter / EagerParamBase,
    python/paddle/base/framework.py)."""
    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, data, dtype=None, trainable=True, name=None):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent."""
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


# -- reference Tensor-method completion (python/paddle/tensor/__init__.py
#    tensor_method_func tail: module-level fns patched as methods) ---------
def _attach_extra_methods():
    """Attach methods whose implementations live outside the op registry
    (linalg composites, signal transforms, framework helpers)."""
    from .. import linalg as _linalg
    from .. import signal as _signal

    Tensor.multi_dot = lambda self, *others: (
        _linalg.multi_dot([self, *others]) if others else self)
    Tensor.stft = lambda self, *a, **k: _signal.stft(self, *a, **k)
    Tensor.istft = lambda self, *a, **k: _signal.istft(self, *a, **k)
    Tensor.is_tensor = lambda self: True
    Tensor.rank = lambda self: self.ndim

    def broadcast_shape(self, y_shape):
        from ..framework.compat import broadcast_shape as _bs
        return _bs(self.shape, y_shape)
    Tensor.broadcast_shape = broadcast_shape

    def create_tensor(self, dtype=None, name=None, persistable=False):
        import jax.numpy as jnp
        from .dtypes import convert_dtype
        return Tensor(jnp.zeros((), convert_dtype(dtype) or self.dtype))
    Tensor.create_tensor = create_tensor

    def create_parameter(self, shape, dtype=None, **kw):
        from ..framework.compat import create_parameter as _cp
        return _cp(shape, dtype or "float32", **kw)
    Tensor.create_parameter = create_parameter

    def set_(self, source=None, shape=None):
        """Rebind this tensor's storage to `source`'s (reference
        Tensor.set_)."""
        if source is not None:
            self._data = source._data if isinstance(source, Tensor) \
                else source
            if shape is not None:
                self._data = self._data.reshape(
                    tuple(int(s) for s in shape))
        return self
    Tensor.set_ = set_

    def resize_(self, shape):
        """Reshape in place, growing/shrinking storage as needed
        (reference Tensor.resize_)."""
        import numpy as np
        import jax.numpy as jnp
        shape = tuple(int(s) for s in shape)
        n_new = int(np.prod(shape)) if shape else 1
        flat = jnp.ravel(self._data)
        if n_new <= flat.shape[0]:
            self._data = flat[:n_new].reshape(shape)
        else:
            pad = jnp.zeros((n_new - flat.shape[0],), flat.dtype)
            self._data = jnp.concatenate([flat, pad]).reshape(shape)
        return self
    Tensor.resize_ = resize_

