"""SelectedRows + StringTensor (reference: paddle/phi/core/selected_rows.h,
paddle/phi/core/string_tensor.h + kernels in paddle/phi/kernels/strings/ —
strings_empty/strings_lower_upper over utf8/unicode case tables).

SelectedRows is the sparse-gradient representation: for an embedding lookup
touching a few vocabulary rows, the weight gradient is (rows, values) pairs
instead of a dense [V, D] array. On TPU the *compute* stays dense-friendly
(values is one [n, D] array — MXU/VPU shaped); sparsity lives in the row
index, and optimizers apply it as a row scatter (`apply_to`), which XLA
lowers to an in-place dynamic-update when the parameter is donated.
"""
import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "StringTensor", "strings_empty", "strings_lower",
           "strings_upper"]


class SelectedRows:
    """rows[i] is the dense row index of values[i]; height is the dense
    leading-dim size (reference selected_rows.h: rows_/value_/height_)."""

    def __init__(self, rows, values, height):
        self.rows = jnp.asarray(rows, jnp.int32).reshape(-1)
        self.values = jnp.asarray(values)
        self.height = int(height)
        if self.values.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"SelectedRows: {self.rows.shape[0]} rows vs "
                f"{self.values.shape[0]} value rows")

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def merge_rows(self):
        """Combine duplicate row ids by summation (reference
        MergeAdd/scatter::MergeAdd) — needed before row-wise optimizer
        updates so each dense row appears once."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.height)
        summed = jax.ops.segment_sum(self.values, inv,
                                     num_segments=uniq.shape[0])
        keep = uniq < self.height
        # mask must broadcast over ANY value rank (1D scalar rows, >2D
        # grads like [n, d1, d2]) — keep[:, None] only fits 2D
        kmask = keep.reshape((-1,) + (1,) * (summed.ndim - 1))
        return SelectedRows(jnp.where(keep, uniq, 0),
                            jnp.where(kmask, summed, 0),
                            self.height)

    def to_dense(self):
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def apply_to(self, dense, scale=1.0):
        """dense - scale * sparse  (SGD-style row update; optimizers call
        this instead of densifying)."""
        return dense.at[self.rows].add(-scale * self.values.astype(
            dense.dtype))

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        return self.to_dense() + other

    __radd__ = __add__

    def numpy(self):
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"n_rows={self.rows.shape[0]}, "
                f"row_dim={tuple(self.values.shape[1:])})")


class StringTensor:
    """Tensor of utf-8 strings (reference string_tensor.h: pstring array +
    dims). Host-resident by design — strings never belong on the MXU; the
    TPU framework keeps them as a numpy object array with the reference's
    kernel surface (empty/lower/upper with an ascii fast path and full
    unicode via Python's casefold machinery, the role of kernels/strings/
    unicode.cc case tables)."""

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return "pstring"

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def lower(self, use_utf8_encoding=True):
        return _case_map(self, str.lower, use_utf8_encoding)

    def upper(self, use_utf8_encoding=True):
        return _case_map(self, str.upper, use_utf8_encoding)

    def __eq__(self, other):
        other_arr = other._data if isinstance(other, StringTensor) else \
            np.asarray(other, dtype=object)
        return self._data == other_arr

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"


def _ascii_only(s):
    try:
        s.encode("ascii")
        return True
    except UnicodeEncodeError:
        return False


def _case_map(st, fn, use_utf8):
    def one(s):
        if not use_utf8 and not _ascii_only(s):
            # ascii mode: leave non-ascii bytes untouched (reference
            # AsciiCaseConverter semantics)
            return "".join(fn(c) if c.isascii() else c for c in s)
        return fn(s)
    out = np.empty(st._data.shape, dtype=object)
    it = np.nditer(st._data, flags=["multi_index", "refs_ok"])
    while not it.finished:
        out[it.multi_index] = one(str(st._data[it.multi_index]))
        it.iternext()
    return StringTensor(out)


def strings_empty(shape):
    """reference strings_empty_kernel: tensor of empty strings."""
    out = np.empty(tuple(shape), dtype=object)
    out.fill("")
    return StringTensor(out)


def strings_lower(x, use_utf8_encoding=True):
    return x.lower(use_utf8_encoding)


def strings_upper(x, use_utf8_encoding=True):
    return x.upper(use_utf8_encoding)
