"""Dtype surface. The reference exposes paddle.float32 etc. backed by
phi::DataType (paddle/phi/common/*); here dtypes are numpy/jnp dtypes directly,
which is what XLA wants. bfloat16 is first-class (TPU-native default for
training compute)."""
import numpy as np
import jax.numpy as jnp
import ml_dtypes

float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
bfloat16 = jnp.bfloat16
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_NAME_TO_DTYPE = {
    "float16": float16, "fp16": float16,
    "float32": float32, "fp32": float32,
    "float64": float64, "fp64": float64,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "bool": bool_,
    "complex64": complex64, "complex128": complex128,
}


def convert_dtype(dtype):
    """Normalize a user-supplied dtype (string / np / jnp) to a numpy dtype.

    With jax x64 disabled (the TPU-native default), 64-bit requests
    canonicalize to 32-bit silently — same behavior as jnp.asarray, minus
    the per-call warning."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = _NAME_TO_DTYPE[dtype]
    d = np.dtype(dtype)
    import jax
    if not jax.config.x64_enabled:
        d = {np.dtype(np.int64): np.dtype(np.int32),
             np.dtype(np.float64): np.dtype(np.float32),
             np.dtype(np.uint64): np.dtype(np.uint32),
             np.dtype(np.complex128): np.dtype(np.complex64)}.get(d, d)
    return d


def is_floating(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.inexact)


def is_integer(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.integer)
