"""Op dispatch: the single choke point every eager op goes through.

Reference analogue: the generated `<op>_ad_func` + PHI API dispatch chain
(paddle/fluid/eager/auto_code_generator/, paddle/phi/api/lib/api.cc via
api_gen.py:544). Here the whole chain collapses to one function: flatten
Tensor args, run the jnp kernel (optionally under `jax.vjp` to capture the
grad closure), wrap outputs. Works identically on concrete arrays (eager)
and on jax tracers (inside jit/to_static), which is what lets the same
layer code serve both execution modes.
"""
import jax
from jax.tree_util import tree_flatten, tree_unflatten

from . import autograd as ag
from .autograd import GradNode

_amp_hook = None  # installed by paddle_tpu.amp; signature (name, args, kwargs) -> (args, kwargs)
_op_tracer = None  # installed by paddle_tpu.profiler; signature (name) -> ctx manager

# ops allowed to consume Partial-placement DTensors (they implement the
# pending reduction); everything else must reshard first
_PARTIAL_OK = {"reshard_p", "to_global", "shard_tensor"}


def set_amp_hook(fn):
    global _amp_hook
    _amp_hook = fn


def set_op_tracer(fn):
    global _op_tracer
    _op_tracer = fn


def apply_op(name, impl, args, kwargs, differentiable=True):
    if _op_tracer is not None:
        with _op_tracer(name):
            return _apply_op_inner(name, impl, args, kwargs, differentiable)
    return _apply_op_inner(name, impl, args, kwargs, differentiable)


def _apply_op_inner(name, impl, args, kwargs, differentiable=True):
    from .tensor import Tensor

    if _amp_hook is not None:
        args, kwargs = _amp_hook(name, args, kwargs)

    leaves, treedef = tree_flatten((args, kwargs),
                                   is_leaf=lambda x: isinstance(x, Tensor))
    tensor_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]

    if name not in _PARTIAL_OK:
        for i in tensor_idx:
            meta = getattr(leaves[i], "_dist_meta", None)
            if meta is not None and meta.partial_axes:
                raise RuntimeError(
                    f"op '{name}' got a Partial-placement DTensor; reshard it "
                    "first (dist.reshard(x, mesh, [Replicate()...]) or "
                    "dist.all_reduce) — partial tensors hold unreduced "
                    "per-device contributions")
    record = (differentiable and ag.is_grad_enabled()
              and any(not leaves[i].stop_gradient for i in tensor_idx))

    plain = list(leaves)
    for i in tensor_idx:
        plain[i] = leaves[i].data

    if not record:
        a, k = tree_unflatten(treedef, plain)
        out = impl(*a, **k)
        return _wrap(name, out, node=None)

    diff_idx = [i for i in tensor_idx if not leaves[i].stop_gradient]
    parents = [leaves[i] for i in diff_idx]

    def fn(*diff_arrays):
        nl = list(plain)
        for j, i in enumerate(diff_idx):
            nl[i] = diff_arrays[j]
        a, k = tree_unflatten(treedef, nl)
        return impl(*a, **k)

    out, vjp_fn = jax.vjp(fn, *(plain[i] for i in diff_idx))
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    node = GradNode(name, vjp_fn, parents,
                    [(o.shape, o.dtype) for o in outs])
    return _wrap(name, out, node=node)


def _wrap(name, out, node):
    from .tensor import Tensor

    def one(arr, idx):
        t = Tensor(arr, stop_gradient=(node is None))
        if node is not None:
            t._node = node
            t._out_idx = idx
        return t

    if isinstance(out, (tuple, list)):
        return tuple(one(o, i) for i, o in enumerate(out))
    return one(out, 0)
