"""Op dispatch: the single choke point every eager op goes through.

Reference analogue: the generated `<op>_ad_func` + PHI API dispatch chain
(paddle/fluid/eager/auto_code_generator/, paddle/phi/api/lib/api.cc via
api_gen.py:544). Here the whole chain collapses to one function: flatten
Tensor args, run the jnp kernel (optionally under `jax.vjp` to capture the
grad closure), wrap outputs. Works identically on concrete arrays (eager)
and on jax tracers (inside jit/to_static), which is what lets the same
layer code serve both execution modes.
"""
import jax
import jax.numpy as jnp
from jax.tree_util import tree_flatten, tree_unflatten

from . import autograd as ag
from .autograd import GradNode


def _block_on(out):
    """FLAGS_benchmark: block until the op's outputs are materialised so
    host wall-time is attributable per-op (reference FLAGS_benchmark forces
    a device sync after each op). No-op under tracing."""
    outs = out if isinstance(out, (tuple, list)) else (out,)
    for o in outs:
        if not isinstance(o, jax.core.Tracer):
            try:
                jax.block_until_ready(o)
            except Exception:
                pass


def _check_nan_inf(name, out):
    """FLAGS_check_nan_inf per-op output watch (reference: per-op check in
    paddle/fluid/eager/nan_inf_utils.cc, flag at paddle/common/flags.cc:72).
    Eager-only: tracers are skipped (inside jit there is no value yet)."""
    import numpy as np
    from ..utils import flags as _flags
    outs = out if isinstance(out, (tuple, list)) else (out,)
    for o in outs:
        if isinstance(o, jax.core.Tracer) or not hasattr(o, "dtype"):
            continue
        if o.dtype.kind != "f" and o.dtype.kind != "c":
            continue
        arr = np.asarray(o)
        bad = ~np.isfinite(arr)
        if bad.any():
            msg = (f"Operator '{name}' output contains "
                   f"{int(np.isnan(arr).sum())} NaN / "
                   f"{int(np.isinf(arr).sum())} Inf values "
                   f"(shape {arr.shape}, dtype {arr.dtype})")
            if _flags.check_nan_inf_level >= 1:
                import warnings
                warnings.warn(msg)
            else:
                raise FloatingPointError(msg)

_amp_hook = None  # installed by paddle_tpu.amp; signature (name, args, kwargs) -> (args, kwargs)
_op_tracer = None  # installed by paddle_tpu.profiler; signature (name) -> ctx manager
_static_recorder = None  # installed by paddle_tpu.static.program_guard
_sir_recorder = None  # installed by the SOT opcode executor during capture
_op_listeners = []  # lightweight observers (SOT statement-IR capture)


def set_static_recorder(r):
    global _static_recorder
    _static_recorder = r


def set_sir_recorder(r):
    """Install the SOT capture hook (rich form: name, impl, treedef, leaves,
    tensor_idx, wrapped — enough to rebuild the op inside a compiled
    segment). Returns the previous hook so nested captures can restore it."""
    global _sir_recorder
    prev = _sir_recorder
    _sir_recorder = r
    return prev


def add_op_listener(fn):
    """Register fn(name, n_inputs, outs) called after every dispatched op
    (works under tracing too — the SOT plane records its StatementIR here)."""
    _op_listeners.append(fn)
    return fn


def remove_op_listener(fn):
    if fn in _op_listeners:
        _op_listeners.remove(fn)


def listener_scope(fn):
    """Context manager form of add/remove_op_listener."""
    import contextlib

    @contextlib.contextmanager
    def _ctx():
        add_op_listener(fn)
        try:
            yield
        finally:
            remove_op_listener(fn)
    return _ctx()


def iter_float_outputs(outs):
    """Yield concrete floating/complex output arrays from a listener's
    `outs` (skips tracers and non-float dtypes; bf16/fp8 are numpy 'V'-kind
    so the check goes through jnp)."""
    import jax
    import jax.numpy as jnp
    outs = outs if isinstance(outs, (tuple, list)) else (outs,)
    for o in outs:
        data = getattr(o, "data", o)
        if isinstance(data, jax.core.Tracer) or not hasattr(data, "dtype"):
            continue
        if not (jnp.issubdtype(data.dtype, jnp.floating)
                or jnp.issubdtype(data.dtype, jnp.complexfloating)):
            continue
        yield data

# ops allowed to consume Partial-placement DTensors (they implement the
# pending reduction); everything else must reshard first
_PARTIAL_OK = {"reshard_p", "to_global", "shard_tensor"}


def set_amp_hook(fn):
    global _amp_hook
    _amp_hook = fn


def set_op_tracer(fn):
    """Install the per-op range hook; returns the previous hook so a
    scoped user (the profiler's record window) restores instead of
    clobbering whatever was installed around it."""
    global _op_tracer
    prev = _op_tracer
    _op_tracer = fn
    return prev


def apply_op(name, impl, args, kwargs, differentiable=True):
    if _op_tracer is not None:
        with _op_tracer(name):
            return _apply_op_inner(name, impl, args, kwargs, differentiable)
    return _apply_op_inner(name, impl, args, kwargs, differentiable)


def _apply_op_inner(name, impl, args, kwargs, differentiable=True):
    from .tensor import Tensor

    if _amp_hook is not None:
        args, kwargs = _amp_hook(name, args, kwargs)

    leaves, treedef = tree_flatten((args, kwargs),
                                   is_leaf=lambda x: isinstance(x, Tensor))
    tensor_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]

    if name not in _PARTIAL_OK:
        for i in tensor_idx:
            meta = getattr(leaves[i], "_dist_meta", None)
            if meta is not None and meta.partial_axes:
                raise RuntimeError(
                    f"op '{name}' got a Partial-placement DTensor; reshard it "
                    "first (dist.reshard(x, mesh, [Replicate()...]) or "
                    "dist.all_reduce) — partial tensors hold unreduced "
                    "per-device contributions")
    record = (differentiable and ag.is_grad_enabled()
              and any(not leaves[i].stop_gradient for i in tensor_idx))

    plain = list(leaves)
    for i in tensor_idx:
        plain[i] = leaves[i].data

    from ..utils import flags as _flags

    if not record:
        a, k = tree_unflatten(treedef, plain)
        out = _canon_out(impl(*a, **k))
        if _flags.check_nan_inf:
            _check_nan_inf(name, out)
        if _flags.benchmark_mode:
            _block_on(out)
        wrapped = _wrap(name, out, node=None)
        if _static_recorder is not None:
            _static_recorder(name, impl, treedef, leaves, tensor_idx,
                             wrapped)
        if _sir_recorder is not None:
            _sir_recorder(name, impl, treedef, leaves, tensor_idx, wrapped)
        for _l in _op_listeners:
            _l(name, len(tensor_idx), wrapped)
        return wrapped

    diff_idx = [i for i in tensor_idx if not leaves[i].stop_gradient]
    parents = [leaves[i] for i in diff_idx]

    def fn(*diff_arrays):
        nl = list(plain)
        for j, i in enumerate(diff_idx):
            nl[i] = diff_arrays[j]
        a, k = tree_unflatten(treedef, nl)
        return _canon_out(impl(*a, **k))

    diff_arrays = tuple(plain[i] for i in diff_idx)
    out, vjp_fn = _vjp_with_cache(name, impl, fn, treedef, plain, diff_idx,
                                  diff_arrays)
    if _flags.check_nan_inf:
        _check_nan_inf(name, out)
    if _flags.benchmark_mode:
        _block_on(out)
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    node = GradNode(name, vjp_fn, parents,
                    [(o.shape, o.dtype) for o in outs],
                    impl=impl, treedef=treedef, plain=plain,
                    diff_idx=diff_idx, multi_out=multi)
    wrapped = _wrap(name, out, node=node)
    if _static_recorder is not None:
        _static_recorder(name, impl, treedef, leaves, tensor_idx, wrapped)
    if _sir_recorder is not None:
        _sir_recorder(name, impl, treedef, leaves, tensor_idx, wrapped)
    for _l in _op_listeners:
        _l(name, len(tensor_idx), wrapped)
    return wrapped


# -- cached eager vjp -------------------------------------------------------
# The reference built PHI to keep the eager per-op path short
# (paddle/phi/README.md §1.2). Here the eager hot cost is jax.vjp re-TRACING
# the kernel on every differentiable call (~0.9ms/op measured on the chip vs
# ~30us for the compiled op itself). Fix: per (op, signature), trace ONCE
# into two jitted executables — a forward, and a backward that re-derives
# the vjp from the saved inputs (rematerialised forward inside the jitted
# backward; jax.jit caches both traces). Eager training trades one extra
# forward in backward for a >10x cut in per-op dispatch latency. Falls back
# to direct jax.vjp for tracers, non-inexact diff inputs, unhashable
# signatures, and impls that draw RNG keys internally (recompute would
# re-draw a different key in backward).

_VJP_CACHE = {}
_VJP_CACHE_MAX = 1024
_VJP_UNCACHEABLE = object()  # negative-cache marker: this sig failed to
                             # trace once (RNG draw, dynamic shapes, ...);
                             # don't pay a failing jit trace on every call


def _cache_put(sig, entry):
    if len(_VJP_CACHE) >= _VJP_CACHE_MAX:
        _VJP_CACHE.pop(next(iter(_VJP_CACHE)))
    _VJP_CACHE[sig] = entry


_RNG_SCAN_CACHE = {}  # code object -> bool (the walk is pure in `code`)


def _impl_draws_rng_cached(impl):
    code = getattr(impl, "__code__", None)
    if code is None:
        return False
    hit = _RNG_SCAN_CACHE.get(code)
    if hit is None:
        hit = _impl_draws_rng(code, getattr(impl, "__globals__", None))
        _RNG_SCAN_CACHE[code] = hit
    return hit


def _impl_draws_rng(code, globs=None, depth=0, seen=None):
    """True if `code` (or a nested/called function, one level of module
    globals deep) draws from the global RNG chain. The callee walk matters:
    an impl calling a module-level helper that draws (`flash_attention` →
    `_sdpa_ref` pre-round-4) is invisible to a co_names scan of the impl
    alone. Belt-and-braces with random.TracedRngError, which makes any
    miss loud instead of state-corrupting."""
    if code is None or depth > 3:
        return False
    if seen is None:
        seen = set()
    if code in seen:
        return False
    seen.add(code)
    names = code.co_names
    if "next_key" in names or "fresh_key_tensor" in names:
        return True
    for c in code.co_consts:
        if hasattr(c, "co_code") and _impl_draws_rng(c, globs, depth + 1, seen):
            return True
    if globs is not None:
        for n in names:
            g = globs.get(n)
            gcode = getattr(g, "__code__", None)
            if gcode is not None and _impl_draws_rng(
                    gcode, getattr(g, "__globals__", None), depth + 1, seen):
                return True
    return False


def _vjp_sig(name, impl, treedef, plain, diff_idx, diff_arrays):
    code = getattr(impl, "__code__", None)
    if code is None:
        return None
    cells = ()
    closure = getattr(impl, "__closure__", None)
    if closure:
        vals = []
        for c in closure:
            try:
                v = c.cell_contents
            except ValueError:
                return None
            if isinstance(v, (bool, int, float, str, bytes, type(None))):
                vals.append(v)
            elif isinstance(v, tuple) and all(
                    isinstance(x, (bool, int, float, str)) for x in v):
                vals.append(v)
            else:
                return None  # captured object: not signature-hashable
        cells = tuple(vals)
    consts = []
    for i, leaf in enumerate(plain):
        if i in diff_idx:
            continue
        if isinstance(leaf, (jax.Array,)) and not isinstance(
                leaf, jax.core.Tracer):
            consts.append(("arr", leaf.shape, str(leaf.dtype)))
        elif isinstance(leaf, (bool, int, float, str, bytes, type(None))):
            consts.append(leaf)
        else:
            return None
    avals = tuple((a.shape, str(a.dtype)) for a in diff_arrays)
    # key by the tuple itself, NOT its hash: dict equality then resolves
    # hash collisions (e.g. hash(-1) == hash(-2) for axis closure cells)
    # instead of silently serving the wrong compiled executable.
    # diff_idx MUST be part of the key: grad w.r.t. x and grad w.r.t. y of
    # a binary op have identical shapes/consts but transpose different
    # arguments — without it the cache served d/dx executables for d/dy
    # (caught by tests/test_op_matrix.py).
    sig = (name, code, cells, treedef, tuple(consts), avals,
           tuple(diff_idx))
    try:
        hash(sig)
    except TypeError:
        return None
    return sig


def _vjp_with_cache(name, impl, fn, treedef, plain, diff_idx, diff_arrays):
    # fallbacks: under tracing, or non-float diff inputs, use direct vjp
    if any(isinstance(a, jax.core.Tracer) for a in plain) or not diff_arrays \
            or any(not jnp.issubdtype(a.dtype, jnp.inexact)
                   for a in diff_arrays):
        return jax.vjp(fn, *diff_arrays)
    sig = _vjp_sig(name, impl, treedef, plain, diff_idx, diff_arrays)
    if sig is None:
        return jax.vjp(fn, *diff_arrays)
    # non-diff array leaves are baked into fn but vary per call: pass them
    # as inputs of the cached executable so values stay correct
    aux_idx = [i for i, leaf in enumerate(plain)
               if i not in diff_idx and isinstance(leaf, jax.Array)]
    if _impl_draws_rng_cached(impl):
        return jax.vjp(fn, *diff_arrays)
    entry = _VJP_CACHE.get(sig)
    if entry is _VJP_UNCACHEABLE:
        return jax.vjp(fn, *diff_arrays)
    if entry is None:

        def make_fn(aux_vals, darrs):
            nl = list(plain)
            for j, i in enumerate(aux_idx):
                nl[i] = aux_vals[j]
            for j, i in enumerate(diff_idx):
                nl[i] = darrs[j]
            a, k = tree_unflatten(treedef, nl)
            return _canon_out(impl(*a, **k))

        def fwd(aux_vals, darrs):
            return make_fn(aux_vals, darrs)

        def bwd(aux_vals, darrs, ct):
            _, vjp = jax.vjp(lambda *d: make_fn(aux_vals, d), *darrs)
            return vjp(ct)

        try:
            fwd_j = jax.jit(fwd)
            bwd_j = jax.jit(bwd)
            aux_vals = tuple(plain[i] for i in aux_idx)
            out = fwd_j(aux_vals, diff_arrays)
        except Exception as e:
            # TracedRngError and trace-structure failures surface here
            # BEFORE any global state was mutated (next_key raises
            # pre-assignment). Negative-cache only *persistent* failure
            # classes; a transient runtime failure (e.g. device OOM during
            # compile) must not disable caching for the process lifetime.
            from .random import TracedRngError
            import jax.errors as _jerr
            if isinstance(e, (TracedRngError, TypeError,
                              _jerr.TracerArrayConversionError,
                              _jerr.ConcretizationTypeError,
                              _jerr.UnexpectedTracerError,
                              _jerr.TracerBoolConversionError)):
                _cache_put(sig, _VJP_UNCACHEABLE)
            return jax.vjp(fn, *diff_arrays)
        _cache_put(sig, (fwd_j, bwd_j))
    else:
        fwd_j, bwd_j = entry
        aux_vals = tuple(plain[i] for i in aux_idx)
        out = fwd_j(aux_vals, diff_arrays)

    def vjp_fn(ct, _aux=aux_vals, _d=diff_arrays, _bwd=bwd_j):
        return _bwd(_aux, _d, ct)

    return out, vjp_fn



def _canon_out(out):
    """jnp APIs return NamedTuples (EighResult, QRResult, SlogdetResult...);
    the tape hands cotangents back as plain tuples and jax.vjp demands the
    EXACT output pytree — canonicalize tuple subclasses at the op boundary
    so forward structure and backward cotangent structure always agree."""
    if isinstance(out, (tuple, list)) and type(out) is not tuple:
        return tuple(out)
    return out


def _wrap(name, out, node):
    from .tensor import Tensor

    def one(arr, idx):
        t = Tensor(arr, stop_gradient=(node is None))
        if node is not None:
            t._node = node
            t._out_idx = idx
        return t

    if isinstance(out, (tuple, list)):
        return tuple(one(o, i) for i, o in enumerate(out))
    return one(out, 0)
