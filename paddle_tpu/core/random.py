"""Global RNG. The reference keeps per-device Generator state with
(seed, offset) philox counters (paddle/phi/core/generator.h:32); on TPU the
idiomatic equivalent is a jax PRNG key chain: `seed()` resets the root key,
every consumer splits one subkey off the chain. Deterministic and
trace-friendly (keys are data, not host state, when used under jit)."""
import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def _get():
    key = getattr(_state, "key", None)
    if key is None:
        key = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.key = key
    return key


def seed(s: int):
    """paddle.seed equivalent: reseed the global generator chain."""
    _state.key = jax.random.PRNGKey(int(s))
    return _state.key


def next_key():
    """Split one subkey off the global chain (host-side eager use)."""
    key = _get()
    _state.key, sub = jax.random.split(key)
    return sub


def get_rng_state():
    return _get()


def set_rng_state(key):
    _state.key = key


def fresh_key_tensor():
    """A PRNG subkey wrapped as a Tensor input leaf. Random ops that take
    their key as an *argument* (instead of drawing inside the impl) stay
    fresh under every capture tier: eager draws per call, jit traces the key
    as an input, and the SOT replay recognizes the marker and re-draws
    (executor._input_locator -> ("rng",))."""
    from .tensor import Tensor
    t = Tensor(next_key())
    t._is_rng_key = True
    return t
