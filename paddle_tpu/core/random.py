"""Global RNG. The reference keeps per-device Generator state with
(seed, offset) philox counters (paddle/phi/core/generator.h:32); on TPU the
idiomatic equivalent is a jax PRNG key chain: `seed()` resets the root key,
every consumer splits one subkey off the chain. Deterministic and
trace-friendly (keys are data, not host state, when used under jit)."""
import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def _get():
    key = getattr(_state, "key", None)
    if key is None:
        key = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.key = key
    return key


def seed(s: int):
    """paddle.seed equivalent: reseed the global generator chain."""
    _state.key = jax.random.PRNGKey(int(s))
    return _state.key


def next_key():
    """Split one subkey off the global chain (host-side eager use)."""
    key = _get()
    _state.key, sub = jax.random.split(key)
    return sub


def get_rng_state():
    return _get()


def set_rng_state(key):
    _state.key = key
