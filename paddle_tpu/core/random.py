"""Global RNG. The reference keeps per-device Generator state with
(seed, offset) philox counters (paddle/phi/core/generator.h:32); on TPU the
idiomatic equivalent is a jax PRNG key chain: `seed()` resets the root key,
every consumer splits one subkey off the chain. Deterministic and
trace-friendly (keys are data, not host state, when used under jit)."""
import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def _get():
    key = getattr(_state, "key", None)
    if key is None:
        key = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.key = key
    return key


def seed(s: int):
    """paddle.seed equivalent: reseed the global generator chain."""
    _state.key = jax.random.PRNGKey(int(s))
    return _state.key


class TracedRngError(RuntimeError):
    """Raised when the global RNG chain would be advanced under an active
    jax trace. Storing a tracer into `_state.key` poisons every later RNG
    consumer with UnexpectedTracerError (global corruption, not a local
    failure). Ops that need randomness under a trace must take their key as
    an input (`fresh_key_tensor()` drawn *outside* the impl) — the philox
    (seed, offset)-as-data discipline of the reference generator
    (paddle/phi/core/generator.h:32)."""


def next_key():
    """Split one subkey off the global chain (host-side eager use).

    Refuses to run under a jax trace: the new chain head would be a tracer
    (see TracedRngError). The eager vjp cache catches this error and falls
    back to the uncached path before any state is mutated."""
    key = _get()
    new_key, sub = jax.random.split(key)
    if isinstance(new_key, jax.core.Tracer):
        raise TracedRngError(
            "next_key() called under an active jax trace; pass the key as "
            "an op input (core.random.fresh_key_tensor()) instead of "
            "drawing inside the kernel impl")
    _state.key = new_key
    return sub


def get_rng_state():
    return _get()


def set_rng_state(key):
    if isinstance(key, jax.core.Tracer):
        raise TracedRngError("set_rng_state() got a tracer; the global RNG "
                             "chain must stay concrete")
    _state.key = key


def fresh_key_tensor():
    """A PRNG subkey wrapped as a Tensor input leaf. Random ops that take
    their key as an *argument* (instead of drawing inside the impl) stay
    fresh under every capture tier: eager draws per call, jit traces the key
    as an input, and the SOT replay recognizes the marker and re-draws
    (executor._input_locator -> ("rng",)).

    Trace-tolerant: under an active jax trace (whole-function to_static
    tier) the chain is NOT advanced — the key is derived by fold_in of a
    host-side counter, so the traced program bakes a fixed key (documented
    limitation of that tier) while the global chain stays concrete."""
    from .tensor import Tensor
    key = _get()
    new_key, sub = jax.random.split(key)
    if isinstance(new_key, jax.core.Tracer):
        _state.trace_draws = getattr(_state, "trace_draws", 0) + 1
        sub = jax.random.fold_in(key, _state.trace_draws)
    else:
        _state.key = new_key
    t = Tensor(sub)
    t._is_rng_key = True
    return t
