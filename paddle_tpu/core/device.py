"""Device handling. The reference's Place/DeviceContext/DeviceManager stack
(paddle/phi/core/device_context.h:37, paddle/phi/backends/device_manager.h:134)
collapses on TPU: PJRT *is* the device plugin ABI, and jax owns contexts and
streams. We keep a thin Place-like API for source compatibility."""
import jax


class Place:
    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)


_current_device = None


def _platform():
    return jax.devices()[0].platform


def set_device(device: str):
    """Accepts 'tpu', 'cpu', 'tpu:0' etc. On this stack data placement is
    managed by jax; this only records intent + validates availability."""
    global _current_device
    kind, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    avail = {d.platform for d in jax.devices()}
    if kind not in avail and kind != "cpu":
        raise ValueError(f"device '{kind}' not available; have {sorted(avail)}")
    _current_device = Place(kind, idx)
    return _current_device


def get_device() -> str:
    if _current_device is not None:
        return f"{_current_device.kind}:{_current_device.index}"
    return f"{_platform()}:0"


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def is_compiled_with_cuda() -> bool:  # source-compat shim
    return False
