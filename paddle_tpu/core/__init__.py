"""Core runtime: Tensor, autograd tape, op dispatch, RNG, device handling.

Plays the role of the reference's PHI core (paddle/phi/core/dense_tensor.h:37,
paddle/fluid/eager/) but TPU-native: the "kernel" for every op is a jax/jnp
function that XLA compiles, and the autograd tape records `jax.vjp` closures
instead of hand-written grad kernels.
"""
from .tensor import Tensor, Parameter, to_tensor
from .autograd import no_grad, enable_grad, is_grad_enabled, set_grad_enabled, backward
from . import dtypes
from .dtypes import (
    float16, float32, float64, bfloat16, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128,
)
from .device import set_device, get_device, device_count, is_compiled_with_tpu
from .random import seed, get_rng_state, set_rng_state, next_key

__all__ = [
    "Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad",
    "is_grad_enabled", "set_grad_enabled", "backward", "dtypes",
    "set_device", "get_device", "device_count", "seed", "next_key",
]
