"""Eager autograd tape.

TPU-native analogue of the reference's eager engine
(paddle/fluid/eager/grad_node_info.h:197, paddle/fluid/eager/backward.cc:473):
each differentiable op call records one `GradNode` holding the `jax.vjp`
closure of its jnp "kernel" (residuals live on device inside the closure, the
moral equivalent of the reference's TensorWrapper saves). `backward()` is a
reverse topological walk with cotangent accumulation.

There are no hand-written grad kernels: `jax.vjp` *is* the grad-kernel
generator, which is the idiomatic XLA replacement for the reference's 345
backward.yaml entries.
"""
import threading

import numpy as np
import jax
import jax.numpy as jnp

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


class _GradModeGuard:
    def __init__(self, mode):
        self._mode = mode

    def __call__(self, func):
        # usable as decorator too, mirroring paddle.no_grad
        def wrapper(*args, **kwargs):
            with self.__class__(self._mode):
                return func(*args, **kwargs)
        return wrapper

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def no_grad(func=None):
    guard = _GradModeGuard(False)
    return guard(func) if callable(func) else _GradModeGuard(False)


def enable_grad(func=None):
    guard = _GradModeGuard(True)
    return guard(func) if callable(func) else _GradModeGuard(True)


class TapeRef:
    """Snapshot of a tensor's tape position at record time. Needed because
    inplace ops rebind the Python Tensor object to a new node (the reference
    tracks this with inplace version counters on TensorWrapper,
    paddle/fluid/eager/tensor_wrapper.h:39); the recorded edge must keep
    pointing at the producing node as of the forward call."""

    __slots__ = ("tensor", "node", "out_idx")

    def __init__(self, tensor):
        self.tensor = tensor
        self.node = tensor._node
        self.out_idx = tensor._out_idx


class GradNode:
    """One recorded op. `vjp_fn` maps output cotangents -> input cotangents
    for the *differentiable* inputs (`parents`, in order)."""

    __slots__ = ("name", "vjp_fn", "parents", "out_avals", "n_outputs")

    def __init__(self, name, vjp_fn, parents, out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = [TapeRef(p) for p in parents]  # strong refs keep graph alive
        self.out_avals = out_avals      # list[(shape, dtype)]
        self.n_outputs = len(out_avals)

    def __repr__(self):
        return f"<GradNode {self.name} n_out={self.n_outputs}>"


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    # integer/bool primal outputs take float0 cotangents in jax
    return np.zeros(shape, jax.dtypes.float0)


def _accumulate(a, b):
    if a is None:
        return b
    return a + b


def backward(tensors, grad_tensors=None, retain_graph=False, _only_leaves=None):
    """Run reverse-mode accumulation from `tensors` (list or single Tensor).

    Mirrors egr::Backward (paddle/fluid/eager/backward.cc:473): seeds the
    output cotangents, walks nodes in reverse topological order, deposits
    into leaf `.grad`, honors per-tensor hooks, frees the graph unless
    retain_graph.
    """
    from .tensor import Tensor  # cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # (node, out_idx) -> cotangent
    cotangents = {}
    roots = []
    for t, g in zip(tensors, grad_tensors):
        seed = g.data if isinstance(g, Tensor) else g
        if seed is None:
            if t.data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {list(t.data.shape)}")
            seed = jnp.ones_like(t.data)
        # hooks fire for roots too (torch/paddle semantics: a tensor's
        # hooks run whenever its gradient is computed, and a backward root
        # receives the seed as its gradient)
        for hook in t._hooks:
            out = hook(t._wrap_grad(seed))
            if out is not None:
                seed = out.data if isinstance(out, Tensor) else out
        if t._node is None:
            if not t.stop_gradient and (_only_leaves is None or id(t) in _only_leaves):
                t._deposit_grad(seed)
            continue
        key = (id(t._node), t._out_idx)
        cotangents[key] = _accumulate(cotangents.get(key), seed)
        roots.append(t._node)

    # topological order (iterative DFS over node graph)
    topo, visited = [], set()
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            topo.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for ref in node.parents:
            if ref.node is not None and id(ref.node) not in visited:
                stack.append((ref.node, False))

    for node in reversed(topo):
        if node.vjp_fn is None:
            raise RuntimeError(
                "trying to backward through the graph a second time: "
                "specify retain_graph=True on the first backward")
        couts = []
        for i, (shape, dtype) in enumerate(node.out_avals):
            c = cotangents.pop((id(node), i), None)
            couts.append(c if c is not None else _zero_cotangent(shape, dtype))
        in_grads = node.vjp_fn(tuple(couts) if node.n_outputs > 1 else couts[0])
        for ref, g in zip(node.parents, in_grads):
            t = ref.tensor
            for hook in t._hooks:
                out = hook(t._wrap_grad(g))
                if out is not None:
                    g = out.data if isinstance(out, Tensor) else out
            if ref.node is None or t._retain_grad:
                if not t.stop_gradient and (_only_leaves is None or id(t) in _only_leaves):
                    t._deposit_grad(g)
            if ref.node is not None:
                key = (id(ref.node), ref.out_idx)
                cotangents[key] = _accumulate(cotangents.get(key), g)
        if not retain_graph:
            node.vjp_fn = None
            node.parents = []


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad equivalent (reference: egr::Grad, backward.cc:490):
    returns grads of `outputs` w.r.t. `inputs` without touching `.grad`.

    Implemented by running the tape walk while capturing cotangents for
    `inputs`. create_graph (higher order) is supported by re-tracing through
    `jax.vjp` of the functionalized subgraph — currently limited to
    create_graph=False on the tape path; use jit/functional API for
    higher-order.
    """
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if create_graph:
        raise NotImplementedError(
            "create_graph=True on the eager tape is not supported yet; "
            "use paddle_tpu.incubate.autograd (functional jax.grad) instead")
    if retain_graph is None:
        retain_graph = False

    # stash and restore .grad of the input leaves, run backward capturing
    # grads ONLY for `inputs` (other leaves' .grad stays untouched)
    stash = [(t, t.grad, t._retain_grad, t.stop_gradient) for t in inputs]
    for t in inputs:
        t.grad = None
        t._retain_grad = True
        t.stop_gradient = False
    try:
        backward(outputs, grad_tensors=grad_outputs, retain_graph=retain_graph,
                 _only_leaves={id(t) for t in inputs})
        result = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise ValueError(
                        "one of the inputs is not reachable from outputs; "
                        "pass allow_unused=True to return None for it")
                result.append(None)
            else:
                result.append(t.grad)
    finally:
        for (t, g, r, s) in stash:
            t.grad = g
            t._retain_grad = r
            t.stop_gradient = s
    return result
