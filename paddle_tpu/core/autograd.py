"""Eager autograd tape.

TPU-native analogue of the reference's eager engine
(paddle/fluid/eager/grad_node_info.h:197, paddle/fluid/eager/backward.cc:473):
each differentiable op call records one `GradNode` holding the `jax.vjp`
closure of its jnp "kernel" (residuals live on device inside the closure, the
moral equivalent of the reference's TensorWrapper saves). `backward()` is a
reverse topological walk with cotangent accumulation.

There are no hand-written grad kernels: `jax.vjp` *is* the grad-kernel
generator, which is the idiomatic XLA replacement for the reference's 345
backward.yaml entries.
"""
import threading

import numpy as np
import jax
import jax.numpy as jnp

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


class _GradModeGuard:
    def __init__(self, mode):
        self._mode = mode

    def __call__(self, func):
        # usable as decorator too, mirroring paddle.no_grad
        def wrapper(*args, **kwargs):
            with self.__class__(self._mode):
                return func(*args, **kwargs)
        return wrapper

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def no_grad(func=None):
    guard = _GradModeGuard(False)
    return guard(func) if callable(func) else _GradModeGuard(False)


def enable_grad(func=None):
    guard = _GradModeGuard(True)
    return guard(func) if callable(func) else _GradModeGuard(True)


class TapeRef:
    """Snapshot of a tensor's tape position at record time. Needed because
    inplace ops rebind the Python Tensor object to a new node (the reference
    tracks this with inplace version counters on TensorWrapper,
    paddle/fluid/eager/tensor_wrapper.h:39); the recorded edge must keep
    pointing at the producing node as of the forward call. `data` snapshots
    the forward-time value so the create_graph re-derivation uses the value
    the op actually saw even if the Python object was later rebound."""

    __slots__ = ("tensor", "node", "out_idx", "data")

    def __init__(self, tensor):
        self.tensor = tensor
        self.node = tensor._node
        self.out_idx = tensor._out_idx
        self.data = tensor._data


class GradNode:
    """One recorded op. `vjp_fn` maps output cotangents -> input cotangents
    for the *differentiable* inputs (`parents`, in order).

    When `impl`/`treedef`/`plain`/`diff_idx` are present (every registry op
    records them via dispatch), the node can also *re-derive* its grads as
    dispatched ops — that is the create_graph=True path (reference: generated
    double/triple-grad nodes, paddle/fluid/eager/backward.cc:490): the vjp is
    re-executed through apply_op so the grad computation itself lands on the
    tape and supports another backward."""

    __slots__ = ("name", "vjp_fn", "parents", "out_avals", "n_outputs",
                 "impl", "treedef", "plain", "diff_idx", "multi_out")

    def __init__(self, name, vjp_fn, parents, out_avals,
                 impl=None, treedef=None, plain=None, diff_idx=None,
                 multi_out=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = [TapeRef(p) for p in parents]  # strong refs keep graph alive
        self.out_avals = out_avals      # list[(shape, dtype)]
        self.n_outputs = len(out_avals)
        # a 1-element TUPLE output must receive a 1-tuple cotangent — the
        # vjp structure follows the impl's return tree, not the count
        self.multi_out = (self.n_outputs > 1 if multi_out is None
                          else bool(multi_out))
        self.impl = impl
        self.treedef = treedef
        self.plain = plain
        self.diff_idx = diff_idx

    def __repr__(self):
        return f"<GradNode {self.name} n_out={self.n_outputs}>"


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    # integer/bool primal outputs take float0 cotangents in jax
    return np.zeros(shape, jax.dtypes.float0)


def _accumulate(a, b):
    if a is None:
        return b
    return a + b


def _node_grad_traced(node, couts):
    """Re-derive one node's input grads as a *dispatched* op, so the grad
    computation is itself recorded on the tape (create_graph=True;
    reference: generated double/triple-grad nodes,
    paddle/fluid/eager/backward.cc:490 + eager_gen.py prim_white_list).
    `couts` holds Tensors for inexact outputs and raw float0 arrays for
    integer outputs. Returns one grad per parent: Tensors for inexact
    parents, float0 arrays otherwise."""
    from .tensor import Tensor
    from .dispatch import apply_op

    if node.impl is None:
        raise RuntimeError(
            f"create_graph=True through '{node.name}' is not supported: the "
            "node records no re-derivable forward (PyLayer/custom ops are "
            "once-differentiable)")
    impl, treedef, plain, diff_idx = (node.impl, node.treedef, node.plain,
                                      node.diff_idx)
    n = len(node.parents)
    prim_in = []
    for ref in node.parents:
        t = ref.tensor
        if t._node is ref.node and t._out_idx == ref.out_idx and t._data is ref.data:
            prim_in.append(t)
        else:  # rebound since forward: reconstruct the forward-time view
            w = Tensor(ref.data, stop_gradient=t.stop_gradient)
            w._node = ref.node
            w._out_idx = ref.out_idx
            prim_in.append(w)
    inexact = [jnp.issubdtype(jnp.result_type(ref.data), jnp.inexact)
               for ref in node.parents]
    if not any(inexact):  # nothing differentiable flows: all grads are float0
        return [np.zeros(jnp.shape(ref.data), jax.dtypes.float0)
                for ref in node.parents]

    def grad_impl(*vals):
        prim, cts = vals[:n], vals[n:]

        def fwd(*darrs):
            nl = list(plain)
            for j, i in enumerate(diff_idx):
                nl[i] = darrs[j]
            a, k = jax.tree_util.tree_unflatten(treedef, nl)
            return impl(*a, **k)

        _, vjp_fn = jax.vjp(fwd, *prim)
        gs = vjp_fn(tuple(cts) if node.multi_out else cts[0])
        traced = [g for g, ok in zip(gs, inexact) if ok]
        return tuple(traced) if len(traced) > 1 else traced[0]

    out = apply_op(node.name + "_grad", grad_impl, (*prim_in, *couts), {})
    outs = list(out) if isinstance(out, (tuple, list)) else [out]
    result, it = [], iter(outs)
    for ref, ok in zip(node.parents, inexact):
        if ok:
            result.append(next(it))
        else:
            result.append(np.zeros(jnp.shape(ref.data), jax.dtypes.float0))
    return result


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False, _only_leaves=None):
    """Run reverse-mode accumulation from `tensors` (list or single Tensor).

    Mirrors egr::Backward (paddle/fluid/eager/backward.cc:473): seeds the
    output cotangents, walks nodes in reverse topological order, deposits
    into leaf `.grad`, honors per-tensor hooks, frees the graph unless
    retain_graph. With create_graph=True every node's grads are computed by
    dispatched ops (_node_grad_traced), so the produced grads carry tape
    nodes and support a further backward()/grad() call — arbitrary-order
    differentiation on the eager tape."""
    from .tensor import Tensor  # cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    if create_graph:
        retain_graph = True

    def as_ct(v):
        # canonical cotangent form for the mode: Tensors when building the
        # grad graph, raw arrays otherwise (float0 and SelectedRows stay
        # as-is)
        from .selected_rows import SelectedRows
        if isinstance(v, SelectedRows):
            return v
        if isinstance(v, Tensor):
            return v if create_graph else v.data
        if not create_graph or getattr(v, "dtype", None) == jax.dtypes.float0:
            return v
        return Tensor(v, stop_gradient=True)

    # (node, out_idx) -> cotangent
    cotangents = {}
    roots = []
    for t, g in zip(tensors, grad_tensors):
        seed = g
        if seed is None:
            if t.data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {list(t.data.shape)}")
            seed = jnp.ones_like(t.data)
        seed = as_ct(seed)
        # hooks fire for roots too (torch/paddle semantics: a tensor's
        # hooks run whenever its gradient is computed, and a backward root
        # receives the seed as its gradient)
        for hook in t._hooks:
            out = hook(t._wrap_grad(seed))
            if out is not None:
                seed = as_ct(out)
        if t._node is None:
            if not t.stop_gradient and (_only_leaves is None or id(t) in _only_leaves):
                t._deposit_grad(seed)
            continue
        key = (id(t._node), t._out_idx)
        cotangents[key] = _accumulate(cotangents.get(key), seed)
        roots.append(t._node)

    # topological order (iterative DFS over node graph)
    topo, visited = [], set()
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            topo.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for ref in node.parents:
            if ref.node is not None and id(ref.node) not in visited:
                stack.append((ref.node, False))

    for node in reversed(topo):
        if node.vjp_fn is None:
            raise RuntimeError(
                "trying to backward through the graph a second time: "
                "specify retain_graph=True on the first backward")
        couts = []
        for i, (shape, dtype) in enumerate(node.out_avals):
            c = cotangents.pop((id(node), i), None)
            if c is None:
                c = as_ct(_zero_cotangent(shape, dtype))
            couts.append(c)
        if create_graph:
            in_grads = _node_grad_traced(node, couts)
        else:
            in_grads = node.vjp_fn(
                tuple(couts) if node.multi_out else couts[0])
        for ref, g in zip(node.parents, in_grads):
            t = ref.tensor
            for hook in t._hooks:
                out = hook(t._wrap_grad(g))
                if out is not None:
                    g = as_ct(out)
            if ref.node is None or t._retain_grad:
                if not t.stop_gradient and (_only_leaves is None or id(t) in _only_leaves):
                    t._deposit_grad(g)
            if ref.node is not None:
                key = (id(ref.node), ref.out_idx)
                cotangents[key] = _accumulate(cotangents.get(key), g)
        if not retain_graph:
            node.vjp_fn = None
            node.parents = []
            node.impl = node.treedef = node.plain = node.diff_idx = None
    # end-of-backward callbacks (reference: the reducer's finalize step,
    # fluid/distributed/collective/reducer.cc — flush partial buckets,
    # handle find_unused_parameters). Suppressed for grad()-style walks
    # (_only_leaves set): grad() must not touch param .grad, so reducer
    # machinery stays out of it entirely.
    if _only_leaves is None:
        for fh in list(_backward_final_hooks):
            fh()


_backward_final_hooks = []


def in_grad_only_walk():
    """True while a grad()-style walk (_only_leaves) is running — reducer
    hooks consult this to pass gradients through untouched."""
    return _grad_only_depth[0] > 0


_grad_only_depth = [0]


def add_backward_final_hook(fn):
    """Register fn() to run after every backward() completes; returns a
    removal handle. Used by the DP EagerReducer to flush tail buckets."""
    _backward_final_hooks.append(fn)

    class _H:
        def remove(self):
            if fn in _backward_final_hooks:
                _backward_final_hooks.remove(fn)
    return _H()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad equivalent (reference: egr::Grad, backward.cc:490):
    returns grads of `outputs` w.r.t. `inputs` without touching `.grad`.

    Implemented by running the tape walk while capturing cotangents for
    `inputs`. With create_graph=True the walk re-derives every node's grads
    through dispatch (_node_grad_traced) so the returned grads are
    themselves differentiable — double/triple grad on the tape.
    """
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        retain_graph = bool(create_graph)

    # stash and restore .grad of the input leaves, run backward capturing
    # grads ONLY for `inputs` (other leaves' .grad stays untouched)
    stash = [(t, t.grad, t._retain_grad, t.stop_gradient) for t in inputs]
    for t in inputs:
        t.grad = None
        t._retain_grad = True
        t.stop_gradient = False
    try:
        _grad_only_depth[0] += 1
        backward(outputs, grad_tensors=grad_outputs, retain_graph=retain_graph,
                 create_graph=create_graph,
                 _only_leaves={id(t) for t in inputs})
        result = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise ValueError(
                        "one of the inputs is not reachable from outputs; "
                        "pass allow_unused=True to return None for it")
                result.append(None)
            else:
                result.append(t.grad)
    finally:
        _grad_only_depth[0] -= 1
        for (t, g, r, s) in stash:
            t.grad = g
            t._retain_grad = r
            t.stop_gradient = s
    return result
