"""KL divergence registry (reference: python/paddle/distribution/kl.py —
register_kl dispatch with MRO-based resolution)."""
import jax.numpy as jnp

from .distribution import Distribution
from ..core.tensor import Tensor

_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def decorator(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return decorator


def _dispatch(p_cls, q_cls):
    matches = [(pc, qc) for pc, qc in _REGISTRY
               if issubclass(p_cls, pc) and issubclass(q_cls, qc)]
    if not matches:
        return None
    # most specific match: smallest MRO distance
    def key(pq):
        pc, qc = pq
        return (p_cls.__mro__.index(pc), q_cls.__mro__.index(qc))
    return _REGISTRY[min(matches, key=key)]


def kl_divergence(p, q):
    fn = _dispatch(type(p), type(q))
    if fn is not None:
        return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


# -- registrations for pairs whose closed form lives on the class ---------
from .continuous import (Normal, LogNormal, Laplace, Cauchy, Exponential,
                         Gamma, Beta, Uniform)
from .discrete import Bernoulli, Categorical, Geometric, Binomial, Poisson
from .multivariate import Dirichlet, MultivariateNormal
from .wrappers import Independent


for cls in (Normal, LogNormal, Laplace, Cauchy, Exponential, Gamma, Beta,
            Bernoulli, Categorical, Geometric, Binomial, Poisson, Dirichlet,
            MultivariateNormal):
    register_kl(cls, cls)(cls.kl_divergence)


@register_kl(Uniform, Normal)
def _kl_uniform_normal(p, q):
    import math
    # E_U[(x-μ)²] = ((b-μ)³ - (a-μ)³) / (3(b-a))
    second_moment = (((p.high - q.loc) ** 3 - (p.low - q.loc) ** 3)
                     / (3 * (p.high - p.low)))
    return Tensor(-jnp.log(p.high - p.low) + jnp.log(q.scale)
                  + 0.5 * math.log(2 * math.pi)
                  + second_moment / (2 * q.scale ** 2))


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    if p.reinterpreted_batch_rank != q.reinterpreted_batch_rank:
        raise NotImplementedError
    inner = kl_divergence(p.base, q.base).data
    axes = tuple(range(-p.reinterpreted_batch_rank, 0))
    return Tensor(jnp.sum(inner, axis=axes) if axes else inner)
