"""Distribution base classes (reference: python/paddle/distribution/
distribution.py, exponential_family.py).

Internals hold jnp arrays; public methods take/return paddle_tpu Tensors.
Sampling draws keys from the global threefry stream (core/random.py) — the
TPU-native counterpart of the reference's philox Generator
(paddle/phi/core/generator.h:32).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random as _random


def _arr(x, dtype=None):
    if isinstance(x, Tensor):
        a = x.data
    else:
        a = jnp.asarray(x, dtype=dtype or jnp.float32)
        if a.dtype == jnp.float64:
            a = a.astype(jnp.float32)
    return a


def _shape(s):
    if s is None:
        return ()
    if isinstance(s, (int, np.integer)):
        return (int(s),)
    return tuple(int(i) for i in s)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape(batch_shape)
        self._event_shape = _shape(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    # -- sampling --------------------------------------------------------
    def _sample(self, key, shape):
        raise NotImplementedError

    def sample(self, shape=()):
        return Tensor(jax.lax.stop_gradient(
            self._sample(_random.next_key(), _shape(shape))))

    def rsample(self, shape=()):
        """Reparameterized sample; grads flow to the parameters."""
        return Tensor(self._sample(_random.next_key(), _shape(shape)))

    # -- densities -------------------------------------------------------
    def _log_prob(self, value):
        raise NotImplementedError

    def log_prob(self, value):
        return Tensor(self._log_prob(_arr(value)))

    def prob(self, value):
        return Tensor(jnp.exp(self._log_prob(_arr(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return _shape(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self._batch_shape}, "
                f"event_shape={self._event_shape})")


class ExponentialFamily(Distribution):
    """Exponential-family base; Bregman-divergence entropy via autodiff of the
    log-normalizer (reference: exponential_family.py uses the same trick)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        # H = A(η) - <η, ∇A(η)> - E[carrier]; ∇A obtained by autodiff of the
        # summed log-normalizer (elementwise families ⇒ per-batch grads)
        nparams = tuple(jnp.asarray(p) for p in self._natural_parameters)
        grads = jax.grad(lambda ps: jnp.sum(self._log_normalizer(*ps)))(nparams)
        ent = self._log_normalizer(*nparams) - self._mean_carrier_measure
        for p, g in zip(nparams, grads):
            ent = ent - p * g
        return Tensor(ent)
