"""Multivariate distributions (reference: python/paddle/distribution/
{dirichlet,multivariate_normal,lkj_cholesky}.py)."""
import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _arr
from .continuous import _bcast
from ..core.tensor import Tensor

_LOG_2PI = math.log(2.0 * math.pi)


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration)
        super().__init__(batch_shape=self.concentration.shape[:-1],
                         event_shape=self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, axis=-1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, axis=-1, keepdims=True)
        a = self.concentration
        return Tensor(a * (a0 - a) / (a0 ** 2 * (a0 + 1)))

    def _sample(self, key, shape):
        return jax.random.dirichlet(key, self.concentration,
                                    shape + self._batch_shape,
                                    dtype=self.concentration.dtype)

    def _log_prob(self, value):
        a = self.concentration
        lnB = jnp.sum(jsp.gammaln(a), axis=-1) - jsp.gammaln(jnp.sum(a, axis=-1))
        return jnp.sum((a - 1) * jnp.log(value), axis=-1) - lnB

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, axis=-1)
        k = a.shape[-1]
        lnB = jnp.sum(jsp.gammaln(a), axis=-1) - jsp.gammaln(a0)
        return Tensor(lnB + (a0 - k) * jsp.digamma(a0)
                      - jnp.sum((a - 1) * jsp.digamma(a), axis=-1))

    def kl_divergence(self, other):
        if isinstance(other, Dirichlet):
            a, b = self.concentration, other.concentration
            a0 = jnp.sum(a, axis=-1, keepdims=True)
            t = jnp.sum((a - b) * (jsp.digamma(a) - jsp.digamma(a0)), axis=-1)
            lnBa = jnp.sum(jsp.gammaln(a), axis=-1) - jsp.gammaln(a0[..., 0])
            lnBb = jnp.sum(jsp.gammaln(b), axis=-1) - jsp.gammaln(jnp.sum(b, axis=-1))
            return Tensor(lnBb - lnBa + t)
        return super().kl_divergence(other)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _arr(loc)
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError("exactly one of covariance_matrix / "
                             "precision_matrix / scale_tril required")
        if scale_tril is not None:
            self._scale_tril = _arr(scale_tril)
        elif covariance_matrix is not None:
            self._scale_tril = jnp.linalg.cholesky(_arr(covariance_matrix))
        else:
            prec = _arr(precision_matrix)
            # chol(P^-1) via inverting the cholesky of P (flip trick keeps it
            # triangular): P = LLᵀ ⇒ Σ = L^-ᵀ L^-1
            Lp = jnp.linalg.cholesky(prec)
            eye = jnp.eye(prec.shape[-1], dtype=prec.dtype)
            Linv = jax.scipy.linalg.solve_triangular(Lp, eye, lower=True)
            self._scale_tril = jnp.linalg.cholesky(
                jnp.swapaxes(Linv, -1, -2) @ Linv)
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self._scale_tril.shape[:-2])
        self.loc = jnp.broadcast_to(self.loc, batch + self.loc.shape[-1:])
        self._scale_tril = jnp.broadcast_to(
            self._scale_tril, batch + self._scale_tril.shape[-2:])
        super().__init__(batch_shape=batch, event_shape=self.loc.shape[-1:])

    @property
    def scale_tril(self):
        return Tensor(self._scale_tril)

    @property
    def covariance_matrix(self):
        L = self._scale_tril
        return Tensor(L @ jnp.swapaxes(L, -1, -2))

    @property
    def precision_matrix(self):
        cov = self.covariance_matrix.data
        return Tensor(jnp.linalg.inv(cov))

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(jnp.sum(self._scale_tril ** 2, axis=-1))

    def _sample(self, key, shape):
        full = shape + self._batch_shape + self._event_shape
        eps = jax.random.normal(key, full, dtype=self.loc.dtype)
        return self.loc + jnp.einsum("...ij,...j->...i", self._scale_tril, eps)

    def _log_prob(self, value):
        diff = value - self.loc
        # solve L y = diff  (triangular) → mahalanobis = |y|^2
        y = jax.scipy.linalg.solve_triangular(
            self._scale_tril, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(y ** 2, axis=-1)
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._scale_tril, axis1=-2, axis2=-1)), axis=-1)
        k = self.loc.shape[-1]
        return -0.5 * (k * _LOG_2PI + maha) - half_logdet

    def entropy(self):
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._scale_tril, axis1=-2, axis2=-1)), axis=-1)
        k = self.loc.shape[-1]
        return Tensor(0.5 * k * (1 + _LOG_2PI) + half_logdet)

    def kl_divergence(self, other):
        if isinstance(other, MultivariateNormal):
            k = self.loc.shape[-1]
            L1, L2 = self._scale_tril, other._scale_tril
            hld1 = jnp.sum(jnp.log(jnp.diagonal(L1, axis1=-2, axis2=-1)), axis=-1)
            hld2 = jnp.sum(jnp.log(jnp.diagonal(L2, axis1=-2, axis2=-1)), axis=-1)
            M = jax.scipy.linalg.solve_triangular(L2, L1, lower=True)
            tr = jnp.sum(M ** 2, axis=(-2, -1))
            diff = other.loc - self.loc
            y = jax.scipy.linalg.solve_triangular(
                L2, diff[..., None], lower=True)[..., 0]
            maha = jnp.sum(y ** 2, axis=-1)
            return Tensor(hld2 - hld1 + 0.5 * (tr + maha - k))
        return super().kl_divergence(other)


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices (reference
    distribution/lkj_cholesky.py; Lewandowski-Kurowicka-Joe 2009). Sampling
    via the onion method; log_prob from the diagonal-power density."""

    def __init__(self, dim=2, concentration=1.0,
                 sample_method="onion", name=None):
        self.dim = int(dim)
        (self.concentration,), shape = _bcast(concentration)
        self.sample_method = sample_method
        super().__init__(batch_shape=shape,
                         event_shape=(self.dim, self.dim))

    def _sample(self, key, shape):
        import jax
        d = self.dim
        eta = self.concentration
        full = shape + self._batch_shape

        def one(k):
            # onion method: build row by row; row i direction uniform on
            # sphere scaled by sqrt(beta sample). Each row consumes TWO
            # independent subkeys (beta radius + normal direction).
            ks = jax.random.split(k, 2 * d)
            L = jnp.zeros((d, d))
            L = L.at[0, 0].set(1.0)
            for i in range(1, d):
                b = eta + (d - 1 - i) / 2.0
                y = jax.random.beta(ks[2 * i], i / 2.0, b)
                u = jax.random.normal(ks[2 * i + 1], (i,))
                u = u / jnp.linalg.norm(u)
                L = L.at[i, :i].set(jnp.sqrt(y) * u)
                L = L.at[i, i].set(jnp.sqrt(1.0 - y))
            return L

        import numpy as np
        n = int(np.prod(full)) if full else 1
        keys = jax.random.split(key, max(n, 1))
        flat = jnp.stack([one(keys[i]) for i in range(n)])
        return flat.reshape(tuple(full) + (d, d)) if full else flat[0]

    def _log_prob(self, value):
        """Density w.r.t. Lebesgue measure on the strictly-lower rows.
        Row r (0-indexed, 1..d-1) contributes L_rr^(2(eta-1) + d-1-r); the
        normalizer comes from the onion factorization: each row's radius
        y=|w|^2 ~ Beta(r/2, eta+(d-1-r)/2) with a uniform sphere direction."""
        d = self.dim
        eta = self.concentration
        diag = jnp.diagonal(value, axis1=-2, axis2=-1)[..., 1:]
        # exponent for row r=1..d-1: 2*(eta-1) + (d-1-r)
        orders = jnp.arange(d - 2, -1, -1) + 2.0 * (eta - 1.0)
        unnorm = jnp.sum(orders * jnp.log(diag), axis=-1)
        r = jnp.arange(1, d)
        b = eta + (d - 1 - r) / 2.0
        lognorm = jnp.sum(r * jnp.log(jnp.pi) / 2.0
                          + jsp.gammaln(b)
                          - jsp.gammaln(b + r / 2.0))
        return unnorm - lognorm
