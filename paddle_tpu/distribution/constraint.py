"""Value constraints (reference: python/paddle/distribution/constraint.py)."""
import jax.numpy as jnp

from .distribution import _arr
from ..core.tensor import Tensor


class Constraint:
    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        v = _arr(value)
        return Tensor(v == v)


class Positive(Constraint):
    def __call__(self, value):
        return Tensor(_arr(value) > 0)


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper

    def __call__(self, value):
        v = _arr(value)
        return Tensor((v >= self._lower) & (v <= self._upper))


class Simplex(Constraint):
    def __call__(self, value):
        v = _arr(value)
        return Tensor((v >= 0).all(axis=-1)
                      & (jnp.abs(v.sum(axis=-1) - 1) < 1e-6))


real = Real()
positive = Positive()
simplex = Simplex()
