"""Probability distributions (reference: python/paddle/distribution/ —
~25 distributions, bijective transforms, KL registry; see SURVEY.md §2.10).

TPU note: sampling is reparameterized wherever the reference's is, and all
math is jnp — distributions compose with jit/pjit and the autograd tape.
"""
from .distribution import Distribution, ExponentialFamily
from .continuous import (Normal, LogNormal, Uniform, Laplace, Gumbel, Cauchy,
                         Exponential, Gamma, Chi2, Beta, StudentT,
                         ContinuousBernoulli)
from .discrete import (Bernoulli, Geometric, Binomial, Categorical,
                       Multinomial, Poisson)
from .multivariate import Dirichlet, MultivariateNormal, LKJCholesky
from .wrappers import Independent, TransformedDistribution
from .transform import (Transform, AffineTransform, ExpTransform,
                        PowerTransform, SigmoidTransform, TanhTransform,
                        AbsTransform, SoftmaxTransform,
                        StickBreakingTransform, StackTransform,
                        ChainTransform, ReshapeTransform,
                        IndependentTransform)
from .kl import kl_divergence, register_kl
from . import constraint
from . import variable

__all__ = [
    "Distribution", "ExponentialFamily", "Normal", "LogNormal", "Uniform",
    "Laplace", "Gumbel", "Cauchy", "Exponential", "Gamma", "Chi2", "Beta",
    "StudentT", "ContinuousBernoulli", "Bernoulli", "Geometric", "Binomial",
    "Categorical", "Multinomial", "Poisson", "Dirichlet",
    "MultivariateNormal", "Independent", "TransformedDistribution",
    "Transform", "AffineTransform", "ExpTransform", "PowerTransform",
    "SigmoidTransform", "TanhTransform", "AbsTransform", "SoftmaxTransform",
    "StickBreakingTransform", "StackTransform", "ChainTransform",
    "ReshapeTransform", "IndependentTransform", "kl_divergence",
    "register_kl", "constraint", "variable",
]
