"""Continuous scalar distributions (reference: python/paddle/distribution/
{normal,uniform,laplace,lognormal,gumbel,cauchy,exponential,gamma,beta,chi2,
student_t,continuous_bernoulli}.py). Math over jnp / jax.random /
jax.scipy.special; sampling reparameterized where the reference's is."""
import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _arr, _shape
from ..core.tensor import Tensor

_LOG_2PI = math.log(2.0 * math.pi)


def _bcast(*xs):
    xs = [_arr(x) for x in xs]
    shape = jnp.broadcast_shapes(*(x.shape for x in xs))
    return [jnp.broadcast_to(x, shape) for x in xs], shape


class Normal(ExponentialFamily):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _bcast(loc, scale)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(self.scale ** 2)

    @property
    def stddev(self):
        return Tensor(self.scale)

    def _sample(self, key, shape):
        eps = jax.random.normal(key, shape + self._batch_shape,
                                dtype=self.loc.dtype)
        return self.loc + self.scale * eps

    def _log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * _LOG_2PI)

    def entropy(self):
        return Tensor(0.5 + 0.5 * _LOG_2PI + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def cdf(self, value):
        return Tensor(0.5 * (1 + jsp.erf(
            (_arr(value) - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        return Tensor(self.loc + self.scale * math.sqrt(2)
                      * jsp.erfinv(2 * _arr(value) - 1))

    def kl_divergence(self, other):
        if isinstance(other, Normal):
            vr = (self.scale / other.scale) ** 2
            t1 = ((self.loc - other.loc) / other.scale) ** 2
            return Tensor(0.5 * (vr + t1 - 1 - jnp.log(vr)))
        return super().kl_divergence(other)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _bcast(loc, scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def _sample(self, key, shape):
        return jnp.exp(self._base._sample(key, shape))

    def _log_prob(self, value):
        return self._base._log_prob(jnp.log(value)) - jnp.log(value)

    def entropy(self):
        return Tensor(self.loc + 0.5 + 0.5 * _LOG_2PI + jnp.log(self.scale))

    def kl_divergence(self, other):
        if isinstance(other, LogNormal):
            return self._base.kl_divergence(other._base)
        return super().kl_divergence(other)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        (self.low, self.high), shape = _bcast(low, high)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor((self.high - self.low) ** 2 / 12)

    def _sample(self, key, shape):
        u = jax.random.uniform(key, shape + self._batch_shape,
                               dtype=self.low.dtype)
        return self.low + (self.high - self.low) * u

    def _log_prob(self, value):
        inside = (value >= self.low) & (value <= self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))

    def cdf(self, value):
        return Tensor(jnp.clip((_arr(value) - self.low)
                               / (self.high - self.low), 0.0, 1.0))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _bcast(loc, scale)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(2 * self.scale ** 2)

    @property
    def stddev(self):
        return Tensor(math.sqrt(2) * self.scale)

    def _sample(self, key, shape):
        u = jax.random.uniform(key, shape + self._batch_shape,
                               dtype=self.loc.dtype, minval=-0.5, maxval=0.5)
        return self.loc - self.scale * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u))

    def _log_prob(self, value):
        return (-jnp.abs(value - self.loc) / self.scale
                - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))

    def cdf(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return Tensor(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        p = _arr(value)
        term = p - 0.5
        return Tensor(self.loc - self.scale * jnp.sign(term)
                      * jnp.log1p(-2 * jnp.abs(term)))

    def kl_divergence(self, other):
        if isinstance(other, Laplace):
            r = self.scale / other.scale
            d = jnp.abs(self.loc - other.loc) / other.scale
            return Tensor(r * jnp.exp(-d / r) + d - 1 + jnp.log(other.scale / self.scale))
        return super().kl_divergence(other)


class Gumbel(Distribution):
    _EULER = 0.5772156649015329

    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _bcast(loc, scale)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * self._EULER)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * self.scale ** 2)

    @property
    def stddev(self):
        return Tensor(math.pi / math.sqrt(6) * self.scale)

    def _sample(self, key, shape):
        g = jax.random.gumbel(key, shape + self._batch_shape,
                              dtype=self.loc.dtype)
        return self.loc + self.scale * g

    def _log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1 + self._EULER
                      + jnp.zeros_like(self.loc))

    def cdf(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(jnp.exp(-jnp.exp(-z)))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = _bcast(loc, scale)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        raise ValueError("Cauchy has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy has no variance")

    def _sample(self, key, shape):
        c = jax.random.cauchy(key, shape + self._batch_shape,
                              dtype=self.loc.dtype)
        return self.loc + self.scale * c

    def _log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -math.log(math.pi) - jnp.log(self.scale) - jnp.log1p(z ** 2)

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale)
                      + jnp.zeros_like(self.loc))

    def cdf(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(jnp.arctan(z) / math.pi + 0.5)

    def kl_divergence(self, other):
        if isinstance(other, Cauchy):
            # closed form (Chyzak & Nielsen 2019)
            num = (self.scale + other.scale) ** 2 + (self.loc - other.loc) ** 2
            den = 4 * self.scale * other.scale
            return Tensor(jnp.log(num / den))
        return super().kl_divergence(other)


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        (self.rate,), shape = _bcast(rate)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / self.rate ** 2)

    def _sample(self, key, shape):
        e = jax.random.exponential(key, shape + self._batch_shape,
                                   dtype=self.rate.dtype)
        return e / self.rate

    def _log_prob(self, value):
        return jnp.where(value >= 0, jnp.log(self.rate) - self.rate * value,
                         -jnp.inf)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))

    def cdf(self, value):
        return Tensor(jnp.clip(-jnp.expm1(-self.rate * _arr(value)), 0.0))

    @property
    def _natural_parameters(self):
        return (-self.rate,)

    def _log_normalizer(self, eta):
        return -jnp.log(-eta)

    def kl_divergence(self, other):
        if isinstance(other, Exponential):
            r = self.rate / other.rate
            return Tensor(jnp.log(r) + 1 / r - 1)
        return super().kl_divergence(other)


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        (self.concentration, self.rate), shape = _bcast(concentration, rate)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def _sample(self, key, shape):
        g = jax.random.gamma(key, self.concentration,
                             shape + self._batch_shape,
                             dtype=self.concentration.dtype)
        return g / self.rate

    def _log_prob(self, value):
        a, b = self.concentration, self.rate
        return (a * jnp.log(b) + (a - 1) * jnp.log(value) - b * value
                - jsp.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return Tensor(a - jnp.log(b) + jsp.gammaln(a)
                      + (1 - a) * jsp.digamma(a))

    def kl_divergence(self, other):
        if isinstance(other, Gamma):
            a1, b1, a2, b2 = (self.concentration, self.rate,
                              other.concentration, other.rate)
            return Tensor((a1 - a2) * jsp.digamma(a1) - jsp.gammaln(a1)
                          + jsp.gammaln(a2) + a2 * (jnp.log(b1) - jnp.log(b2))
                          + a1 * (b2 / b1 - 1))
        return super().kl_divergence(other)


class Chi2(Gamma):
    def __init__(self, df, name=None):
        (df,), _ = _bcast(df)
        self.df = df
        super().__init__(df / 2.0, jnp.full_like(df, 0.5))


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        (self.alpha, self.beta), shape = _bcast(alpha, beta)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s ** 2 * (s + 1)))

    def _sample(self, key, shape):
        return jax.random.beta(key, self.alpha, self.beta,
                               shape + self._batch_shape,
                               dtype=self.alpha.dtype)

    def _log_prob(self, value):
        a, b = self.alpha, self.beta
        return ((a - 1) * jnp.log(value) + (b - 1) * jnp.log1p(-value)
                - (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)))

    def entropy(self):
        a, b = self.alpha, self.beta
        lbeta = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
        return Tensor(lbeta - (a - 1) * jsp.digamma(a)
                      - (b - 1) * jsp.digamma(b)
                      + (a + b - 2) * jsp.digamma(a + b))

    def kl_divergence(self, other):
        if isinstance(other, Beta):
            a1, b1, a2, b2 = self.alpha, self.beta, other.alpha, other.beta
            lbeta1 = jsp.gammaln(a1) + jsp.gammaln(b1) - jsp.gammaln(a1 + b1)
            lbeta2 = jsp.gammaln(a2) + jsp.gammaln(b2) - jsp.gammaln(a2 + b2)
            return Tensor(lbeta2 - lbeta1
                          + (a1 - a2) * jsp.digamma(a1)
                          + (b1 - b2) * jsp.digamma(b1)
                          + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1))
        return super().kl_divergence(other)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        (self.df, self.loc, self.scale), shape = _bcast(df, loc, scale)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        v = jnp.where(self.df > 2,
                      self.scale ** 2 * self.df / (self.df - 2),
                      jnp.where(self.df > 1, jnp.inf, jnp.nan))
        return Tensor(v)

    def _sample(self, key, shape):
        t = jax.random.t(key, self.df, shape + self._batch_shape,
                         dtype=self.loc.dtype)
        return self.loc + self.scale * t

    def _log_prob(self, value):
        df = self.df
        z = (value - self.loc) / self.scale
        return (jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
                - 0.5 * jnp.log(df * math.pi) - jnp.log(self.scale)
                - (df + 1) / 2 * jnp.log1p(z ** 2 / df))

    def entropy(self):
        df = self.df
        return Tensor((df + 1) / 2 * (jsp.digamma((df + 1) / 2)
                                      - jsp.digamma(df / 2))
                      + 0.5 * jnp.log(df)
                      + jsp.gammaln(df / 2) + jsp.gammaln(0.5)
                      - jsp.gammaln((df + 1) / 2)
                      + jnp.log(self.scale))


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        (self.probs,), shape = _bcast(probs)
        self._lims = lims
        super().__init__(batch_shape=shape)

    def _outside(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _cut_probs(self):
        return jnp.where(self._outside(), self.probs,
                         jnp.full_like(self.probs, self._lims[0]))

    def _log_norm_const(self):
        # log C(p); taylor expansion near p=0.5 for stability
        p = self._cut_probs()
        exact = jnp.log(jnp.abs(jnp.arctanh(1 - 2 * p))
                        / jnp.abs(1 - 2 * p) * 2)
        x = self.probs - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x ** 2) * x ** 2
        return jnp.where(self._outside(), exact, taylor)

    @property
    def mean(self):
        p = self._cut_probs()
        exact = p / (2 * p - 1) + 1 / (2 * jnp.arctanh(1 - 2 * p))
        x = self.probs - 0.5
        taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x ** 2) * x
        return Tensor(jnp.where(self._outside(), exact, taylor))

    @property
    def variance(self):
        p = self._cut_probs()
        exact = p * (p - 1) / (1 - 2 * p) ** 2 + 1 / (2 * jnp.arctanh(1 - 2 * p)) ** 2
        x = self.probs - 0.5
        taylor = 1.0 / 12.0 - (1.0 / 15.0 - 128.0 / 945.0 * x ** 2) * x ** 2
        return Tensor(jnp.where(self._outside(), exact, taylor))

    def _sample(self, key, shape):
        u = jax.random.uniform(key, shape + self._batch_shape,
                               dtype=self.probs.dtype)
        p = self._cut_probs()
        # inverse-cdf: x = log1p(u*(2p-1)/(1-p)) / log(p/(1-p))
        icdf = jnp.log1p((2 * p - 1) * u / (1 - p)) / jnp.log(p / (1 - p))
        return jnp.where(self._outside(), icdf, u)

    def _log_prob(self, value):
        p = self.probs
        return (value * jnp.log(p) + (1 - value) * jnp.log1p(-p)
                + self._log_norm_const())

    def entropy(self):
        m = self.mean.data
        p = self.probs
        return Tensor(-(m * jnp.log(p) + (1 - m) * jnp.log1p(-p)
                        + self._log_norm_const()))
