"""Bijective transforms (reference: python/paddle/distribution/transform.py).

Each Transform supplies forward/inverse and the log|det J|; variable types
mirror the reference (Type.BIJECTION etc. collapse to a bool here).
"""
import math

import jax
import jax.numpy as jnp

from .distribution import _arr
from ..core.tensor import Tensor


class Transform:
    _is_injective = True

    @property
    def inv(self):
        return _InverseTransform(self)

    def forward(self, x):
        return Tensor(self._forward(_arr(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._forward_log_det_jacobian(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        y = _arr(y)
        return Tensor(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # subclass hooks over jnp arrays
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class _InverseTransform(Transform):
    def __init__(self, base):
        self._base = base

    def _forward(self, x):
        return self._base._inverse(x)

    def _inverse(self, y):
        return self._base._forward(y)

    def _forward_log_det_jacobian(self, x):
        return -self._base._forward_log_det_jacobian(self._base._inverse(x))


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2(log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    _is_injective = False

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class SoftmaxTransform(Transform):
    _is_injective = False

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("softmax is not a diffeomorphism")


class StickBreakingTransform(Transform):
    """Unconstrained R^{K-1} → simplex Δ^K (reference transform.py)."""

    def _forward(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], axis=-1)
        one_minus = jnp.concatenate(
            [jnp.ones_like(z[..., :1]), 1 - z], axis=-1)
        return zpad * jnp.cumprod(one_minus, axis=-1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        rem = 1 - jnp.cumsum(y_crop, axis=-1)
        offset = y_crop.shape[-1] - jnp.arange(y_crop.shape[-1],
                                               dtype=y.dtype)
        z = y_crop / jnp.concatenate(
            [jnp.ones_like(rem[..., :1]), rem[..., :-1]], axis=-1)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        # triangular jacobian: dy_k/dx_k = c_k σ'(u_k), c_k = Π_{j<k}(1-z_j)
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        u = x - jnp.log(offset)
        z = jax.nn.sigmoid(u)
        stick = jnp.cumprod(1 - z, axis=-1)
        c = jnp.concatenate([jnp.ones_like(z[..., :1]), stick[..., :-1]],
                            axis=-1)
        return jnp.sum(jnp.log(c) - jax.nn.softplus(u) - jax.nn.softplus(-u),
                       axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _split(self, x):
        return [jnp.squeeze(s, self.axis) for s in
                jnp.split(x, x.shape[self.axis], axis=self.axis)]

    def _forward(self, x):
        return jnp.stack([t._forward(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)

    def _inverse(self, y):
        return jnp.stack([t._inverse(s) for t, s in
                          zip(self.transforms, self._split(y))], self.axis)

    def _forward_log_det_jacobian(self, x):
        return jnp.stack([t._forward_log_det_jacobian(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        ld = 0.0
        for t in self.transforms:
            ld = ld + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return ld


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, dtype=x.dtype)


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self.base._forward_log_det_jacobian(x)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(ld, axis=axes)
