"""Independent + TransformedDistribution wrappers (reference:
python/paddle/distribution/{independent,transformed_distribution}.py)."""
import jax.numpy as jnp

from .distribution import Distribution, _arr, _shape
from ..core.tensor import Tensor


class Independent(Distribution):
    """Reinterpret trailing batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        if self.reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        k = self.reinterpreted_batch_rank
        super().__init__(
            batch_shape=base.batch_shape[:len(base.batch_shape) - k],
            event_shape=base.batch_shape[len(base.batch_shape) - k:]
            + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def _sample(self, key, shape):
        return self.base._sample(key, shape)

    def _log_prob(self, value):
        lp = self.base._log_prob(value)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(lp, axis=axes) if axes else lp

    def entropy(self):
        ent = self.base.entropy().data
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return Tensor(jnp.sum(ent, axis=axes) if axes else ent)


class TransformedDistribution(Distribution):
    """Push a base distribution through a chain of transforms."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        shape = base.batch_shape + base.event_shape
        for t in self.transforms:
            shape = t.forward_shape(shape)
        # keep base's batch/event split convention on the transformed shape
        nb = len(base.batch_shape)
        super().__init__(batch_shape=shape[:nb], event_shape=shape[nb:])

    def _sample(self, key, shape):
        x = self.base._sample(key, shape)
        for t in self.transforms:
            x = t._forward(x)
        return x

    def sample(self, shape=()):
        from ..core import random as _random
        import jax
        return Tensor(jax.lax.stop_gradient(
            self._sample(_random.next_key(), _shape(shape))))

    def rsample(self, shape=()):
        from ..core import random as _random
        return Tensor(self._sample(_random.next_key(), _shape(shape)))

    def _log_prob(self, value):
        # log p(y) = log p_base(x) - Σ log|det J|, each summed down to this
        # distribution's batch shape (torch/paddle shape algebra)
        event_rank = len(self._event_shape)
        lp = 0.0
        y = value
        for t in reversed(self.transforms):
            x = t._inverse(y)
            ld = t._forward_log_det_jacobian(x)
            lp = lp - _sum_rightmost(ld, event_rank - (y.ndim - ld.ndim))
            y = x
        base_lp = self.base._log_prob(y)
        lp = lp + _sum_rightmost(base_lp,
                                 event_rank - len(self.base.event_shape))
        return lp


def _sum_rightmost(x, n):
    if n <= 0:
        return x
    return jnp.sum(x, axis=tuple(range(-n, 0)))
