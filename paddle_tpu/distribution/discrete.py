"""Discrete distributions (reference: python/paddle/distribution/
{bernoulli,binomial,categorical,geometric,multinomial,poisson}.py)."""
import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _arr
from ..core.tensor import Tensor


def _bcast(*xs):
    xs = [_arr(x) for x in xs]
    shape = jnp.broadcast_shapes(*(x.shape for x in xs))
    return [jnp.broadcast_to(x, shape) for x in xs], shape


class Bernoulli(ExponentialFamily):
    def __init__(self, probs, name=None):
        (self.probs,), shape = _bcast(probs)
        super().__init__(batch_shape=shape)

    @property
    def logits(self):
        return Tensor(jnp.log(self.probs) - jnp.log1p(-self.probs))

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def _sample(self, key, shape):
        return jax.random.bernoulli(
            key, self.probs, shape + self._batch_shape).astype(self.probs.dtype)

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (reference bernoulli.py rsample)."""
        from ..core import random as _random
        shape = tuple(shape)
        u = jax.random.uniform(_random.next_key(),
                               shape + self._batch_shape,
                               dtype=self.probs.dtype, minval=1e-6,
                               maxval=1.0 - 1e-6)
        logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        noise = jnp.log(u) - jnp.log1p(-u)
        return Tensor(jax.nn.sigmoid((logits + noise) / temperature))

    def _log_prob(self, value):
        return (value * jnp.log(self.probs)
                + (1 - value) * jnp.log1p(-self.probs))

    def entropy(self):
        p = self.probs
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    def cdf(self, value):
        v = _arr(value)
        return Tensor(jnp.where(v < 0, 0.0,
                                jnp.where(v < 1, 1 - self.probs, 1.0)))

    def kl_divergence(self, other):
        if isinstance(other, Bernoulli):
            p, q = self.probs, other.probs
            return Tensor(p * (jnp.log(p) - jnp.log(q))
                          + (1 - p) * (jnp.log1p(-p) - jnp.log1p(-q)))
        return super().kl_divergence(other)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs, name=None):
        (self.probs,), shape = _bcast(probs)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    @property
    def stddev(self):
        return Tensor(jnp.sqrt((1 - self.probs)) / self.probs)

    def _sample(self, key, shape):
        u = jax.random.uniform(key, shape + self._batch_shape,
                               dtype=self.probs.dtype, minval=1e-12)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def _log_prob(self, value):
        return value * jnp.log1p(-self.probs) + jnp.log(self.probs)

    def pmf(self, k):
        return self.prob(k)

    def entropy(self):
        p = self.probs
        return Tensor(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)

    def cdf(self, k):
        return Tensor(1 - jnp.power(1 - self.probs, _arr(k) + 1))

    def kl_divergence(self, other):
        if isinstance(other, Geometric):
            # E[k] = (1-p)/p trials weight the continuation term
            p, q = self.probs, other.probs
            return Tensor(jnp.log(p / q)
                          + (1 - p) / p * jnp.log((1 - p) / (1 - q)))
        return super().kl_divergence(other)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        (tc, self.probs), shape = _bcast(total_count, probs)
        self.total_count = tc.astype(self.probs.dtype)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, key, shape):
        n_max = int(jnp.max(self.total_count))
        full = shape + self._batch_shape
        u = jax.random.uniform(key, (n_max,) + full, dtype=self.probs.dtype)
        trials = (u < self.probs).astype(self.probs.dtype)
        idx = jnp.arange(n_max).reshape((n_max,) + (1,) * len(full))
        mask = idx < self.total_count
        return jnp.sum(trials * mask, axis=0)

    def _log_prob(self, value):
        n, p = self.total_count, self.probs
        logc = (jsp.gammaln(n + 1) - jsp.gammaln(value + 1)
                - jsp.gammaln(n - value + 1))
        return logc + value * jnp.log(p) + (n - value) * jnp.log1p(-p)

    def entropy(self):
        # exact by enumeration over support (reference binomial.py does same)
        n_max = int(jnp.max(self.total_count))
        ks = jnp.arange(0, n_max + 1, dtype=self.probs.dtype)
        ks = ks.reshape((n_max + 1,) + (1,) * len(self._batch_shape))
        lp = self._log_prob(ks)
        valid = ks <= self.total_count
        return Tensor(-jnp.sum(jnp.where(valid, jnp.exp(lp) * lp, 0.0), axis=0))

    def kl_divergence(self, other):
        if isinstance(other, Binomial):
            p, q = self.probs, other.probs
            n = self.total_count
            return Tensor(n * (p * jnp.log(p / q)
                               + (1 - p) * jnp.log((1 - p) / (1 - q))))
        return super().kl_divergence(other)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("either logits or probs required")
        if logits is not None:
            self.logits = _arr(logits)
            self._probs = jax.nn.softmax(self.logits, axis=-1)
        else:
            self._probs = _arr(probs) / jnp.sum(_arr(probs), axis=-1,
                                                keepdims=True)
            self.logits = jnp.log(self._probs)
        super().__init__(batch_shape=self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor(self._probs)

    @property
    def num_categories(self):
        return self.logits.shape[-1]

    def _sample(self, key, shape):
        return jax.random.categorical(key, self.logits,
                                      shape=shape + self._batch_shape)

    def sample(self, shape=()):
        from ..core import random as _random
        return Tensor(self._sample(_random.next_key(), tuple(shape)))

    def _log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        idx = value.astype(jnp.int32)
        logp = jnp.broadcast_to(logp, idx.shape + logp.shape[-1:])
        return jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]

    def probabilities(self, value):
        return self.prob(value)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(self._probs * logp, axis=-1))

    def kl_divergence(self, other):
        if isinstance(other, Categorical):
            lp = jax.nn.log_softmax(self.logits, axis=-1)
            lq = jax.nn.log_softmax(other.logits, axis=-1)
            return Tensor(jnp.sum(self._probs * (lp - lq), axis=-1))
        return super().kl_divergence(other)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _arr(probs)
        self._probs = p / jnp.sum(p, axis=-1, keepdims=True)
        super().__init__(batch_shape=p.shape[:-1], event_shape=p.shape[-1:])

    @property
    def probs(self):
        return Tensor(self._probs)

    @property
    def mean(self):
        return Tensor(self.total_count * self._probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self._probs * (1 - self._probs))

    def _sample(self, key, shape):
        logits = jnp.log(self._probs)
        draws = jax.random.categorical(
            key, logits, shape=(self.total_count,) + shape + self._batch_shape)
        k = self._probs.shape[-1]
        onehot = jax.nn.one_hot(draws, k, dtype=self._probs.dtype)
        return jnp.sum(onehot, axis=0)

    def _log_prob(self, value):
        logc = (jsp.gammaln(jnp.asarray(self.total_count + 1.0))
                - jnp.sum(jsp.gammaln(value + 1.0), axis=-1))
        return logc + jnp.sum(value * jnp.log(self._probs), axis=-1)

    def entropy(self):
        # Monte-Carlo-free bound is complex; use E[-log p] over samples of the
        # per-trial categorical scaled — reference uses enumeration for small n.
        n = self.total_count
        p = self._probs
        cat_ent = -jnp.sum(p * jnp.log(p), axis=-1)
        # exact for n==1, standard approximation otherwise
        if n == 1:
            return Tensor(cat_ent)
        k = p.shape[-1]
        approx = (0.5 * jnp.log((2 * math.pi * math.e * n) ** (k - 1)
                                * jnp.prod(p, axis=-1)))
        return Tensor(approx)


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        (self.rate,), shape = _bcast(rate)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def _sample(self, key, shape):
        return jax.random.poisson(key, self.rate,
                                  shape + self._batch_shape).astype(self.rate.dtype)

    def _log_prob(self, value):
        return (value * jnp.log(self.rate) - self.rate
                - jsp.gammaln(value + 1))

    def entropy(self):
        # series approximation capped by enumeration for small rates
        lam = self.rate
        n_max = max(20, int(jnp.max(lam)) * 3 + 10)
        ks = jnp.arange(0, n_max, dtype=lam.dtype)
        ks = ks.reshape((n_max,) + (1,) * len(self._batch_shape))
        lp = self._log_prob(ks)
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=0))

    def kl_divergence(self, other):
        if isinstance(other, Poisson):
            r, s = self.rate, other.rate
            return Tensor(r * jnp.log(r / s) + s - r)
        return super().kl_divergence(other)
