"""Random-variable domain descriptors (reference:
python/paddle/distribution/variable.py)."""
from . import constraint


class Variable:
    def __init__(self, is_discrete=False, event_rank=0, constraint=None):
        self.is_discrete = is_discrete
        self.event_rank = event_rank
        self._constraint = constraint

    def constraint_check(self, value):
        return self._constraint(value)


class Real(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, constraint.real)


class Positive(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, constraint.positive)


class Independent(Variable):
    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__(base.is_discrete,
                         base.event_rank + reinterpreted_batch_rank,
                         base._constraint)


class Stack(Variable):
    def __init__(self, vars, axis=0):
        self._vars = vars
        self._axis = axis
        super().__init__(any(v.is_discrete for v in vars),
                         max(v.event_rank for v in vars))


real = Real()
positive = Positive()
