"""Model summary (reference: python/paddle/hapi/model_summary.py)."""
import numpy as np

from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table and return {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []
    from ..nn.layer import Layer

    def make_hook(name):
        def hook(layer, inputs, outputs):
            n_params = sum(p.size for p in layer._parameters.values()
                           if p is not None)
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "-"
            rows.append((name, type(layer).__name__, shape, n_params))
        return hook

    for name, sub in net.named_sublayers(include_self=False):
        if not sub._sub_layers:  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name)))

    if input is not None:
        x = input
        net(x)
    elif input_size is not None:
        from .. import ops
        shape = list(input_size)
        x = ops.zeros(shape, dtypes or "float32")
        net(x)
    for h in hooks:
        h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if p.trainable)
    if rows:
        w = max(len(r[0]) for r in rows) + 2
        print(f"{'Layer':<{w}}{'Type':<20}{'Output Shape':<20}{'Params':>10}")
        print("-" * (w + 50))
        for name, t, shape, n in rows:
            print(f"{name:<{w}}{t:<20}{str(shape):<20}{n:>10}")
        print("-" * (w + 50))
    print(f"Total params: {total}")
    print(f"Trainable params: {trainable}")
    return {"total_params": int(total), "trainable_params": int(trainable)}
