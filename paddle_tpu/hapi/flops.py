"""paddle.flops (reference: python/paddle/hapi/dynamic_flops.py — per-layer
FLOPs via forward hooks + a per-type count table)."""
import numpy as np

from ..core.tensor import Tensor
from .. import nn

__all__ = ["flops"]


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _count_linear(layer, x, y):
    return _numel(x.shape) // x.shape[-1] * layer.weight.shape[0] \
        * layer.weight.shape[1]


def _count_conv(layer, x, y):
    kernel = _numel(layer.weight.shape[2:])
    cin = layer.weight.shape[1]
    return _numel(y.shape) * cin * kernel


def _count_norm(layer, x, y):
    return 2 * _numel(x.shape)


def _count_act(layer, x, y):
    return _numel(x.shape)


_TABLE = [
    (nn.Linear, _count_linear),
    (nn.Conv1D, _count_conv), (nn.Conv2D, _count_conv),
    (nn.Conv3D, _count_conv),
    (nn.BatchNorm1D, _count_norm), (nn.BatchNorm2D, _count_norm),
    (nn.LayerNorm, _count_norm),
    (nn.ReLU, _count_act), (nn.GELU, _count_act), (nn.Sigmoid, _count_act),
]


def _counter_for(layer):
    for cls, fn in _TABLE:
        if isinstance(layer, cls):
            return fn
    return None


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Total forward FLOPs (multiply-accumulate counted as 2 ops matches
    the reference's convention of 1 MAC -> counted once; we follow the
    reference: conv/linear counted as MACs)."""
    custom_ops = custom_ops or {}
    counts = {}
    handles = []

    def make_hook(name, layer):
        def hook(ly, inp, out):
            x = inp[0] if isinstance(inp, (tuple, list)) else inp
            fn = custom_ops.get(type(ly)) or _counter_for(ly)
            if fn is not None and isinstance(x, Tensor):
                counts[name] = counts.get(name, 0) + int(fn(ly, x, out))
        return hook

    for name, layer in net.named_sublayers(include_self=True):
        if not layer._sub_layers:  # leaves only (incl. a leaf root)
            handles.append(layer.register_forward_post_hook(
                make_hook(name or type(layer).__name__, layer)))

    import paddle_tpu as paddle
    if inputs is None:
        if input_size is None:
            raise ValueError("flops needs input_size or inputs")
        inputs = (paddle.to_tensor(
            np.zeros(input_size, np.float32)),)
    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        if was_training:
            net.train()
        for h in handles:
            h.remove()
    total = sum(counts.values())
    if print_detail:
        for k, v in sorted(counts.items(), key=lambda kv: -kv[1]):
            print(f"{k:50s} {v:>15,d}")
        print(f"{'Total':50s} {total:>15,d}")
    return total
