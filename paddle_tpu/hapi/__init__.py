"""paddle hapi (reference: python/paddle/hapi/)."""
from .model import Model
from .summary import summary
from . import callbacks
