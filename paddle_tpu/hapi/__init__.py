"""Placeholder — populated at M2."""
Model = None
def summary(*a, **k):
    raise NotImplementedError
