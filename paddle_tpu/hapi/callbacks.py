"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
import time

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        pass

    def on_batch_end(self, mode, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks
        self.stop_training = False

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)
            c._list = self

    def __getattr__(self, name):
        raise AttributeError(name)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.set_params(logs or {})
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_begin(mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_begin(self, mode, logs=None):
        # monotonic, not time.time(): these stamps only ever feed
        # durations, and an NTP step mid-epoch would corrupt them (GL111)
        self._start = time.monotonic()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._epoch_start = time.monotonic()

    def on_batch_end(self, mode, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                               if isinstance(v, (int, float)) and k != "step")
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.monotonic() - self._epoch_start
            items = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                               if isinstance(v, (int, float)) and k != "step")
            print(f"Epoch {epoch} done in {dt:.1f}s: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = None
        self.wait = 0
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_epoch_end(self, epoch, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        better = (self.best is None
                  or (self.mode == "min" and val < self.best - self.min_delta)
                  or (self.mode == "max" and val > self.best + self.min_delta))
        if better:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                getattr(self, "_list", None) and setattr(self._list, "stop_training", True)


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each batch or epoch."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None)

    def on_batch_end(self, mode, step, logs=None):
        if mode == "train" and self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()
