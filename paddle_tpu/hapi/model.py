"""hapi Model (reference: python/paddle/hapi/model.py:1472,2200 — Keras-like
fit/evaluate/predict + callbacks)."""
import time

import numpy as np

from ..core.tensor import Tensor
from ..core import autograd as ag
from ..io import DataLoader
from .callbacks import CallbackList, ProgBarLogger


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _update_metric(metric, out, label):
    """Metric.compute may return a single array or a tuple of update() args
    (the base Metric.compute passes through (pred, label))."""
    res = metric.compute(out, label)
    if isinstance(res, tuple):
        metric.update(*res)
    else:
        metric.update(res)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._jit = False
        self._train_fn = None

    def prepare(self, optimizer=None, loss=None, metrics=None, jit=False,
                amp_configs=None):
        """jit=True compiles forward+loss into one XLA program per signature
        (to_static over the loss graph). Leave False for models whose layers
        mutate host state in forward (BatchNorm running stats)."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        self._jit = jit
        if jit:
            from ..jit import to_static
            network = self.network
            loss_fn = loss

            def fwd_loss(x, y):
                out = network(x)
                return loss_fn(out, y), out
            self._train_fn = to_static(fwd_loss)
        return self

    # -- single steps -----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        y = labels[0] if isinstance(labels, (list, tuple)) else labels
        if self._train_fn is not None:
            loss, out = self._train_fn(x, y)
        else:
            out = self.network(x)
            loss = self._loss(out, y)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        for m in self._metrics:
            _update_metric(m, out.detach(), y)
        return loss

    @ag.no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        y = labels[0] if isinstance(labels, (list, tuple)) else labels
        out = self.network(x)
        loss = self._loss(out, y) if self._loss else None
        for m in self._metrics:
            _update_metric(m, out, y)
        return loss, out

    @ag.no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        return self.network(x)

    # -- loops ------------------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._to_loader(train_data, batch_size, shuffle, num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False, num_workers)
        cbs = CallbackList(_as_list(callbacks) or [ProgBarLogger(log_freq, verbose)])
        cbs.set_model(self)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbs.on_begin("train", {"epochs": epochs, "steps": steps,
                               "metrics": self._metric_names()})
        stop = False
        for epoch in range(epochs):
            if stop:
                break
            cbs.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbs.on_batch_begin("train", step, logs)
                x, y = batch[0], batch[1] if len(batch) > 1 else None
                update = ((step + 1) % accumulate_grad_batches == 0)
                loss = self.train_batch(x, y, update=update)
                logs = {"loss": float(loss.item()), "step": step}
                for m in self._metrics:
                    res = m.accumulate()
                    names = m.name() if isinstance(m.name(), list) else [m.name()]
                    vals = res if isinstance(res, list) else [res]
                    logs.update(dict(zip(names, vals)))
                cbs.on_batch_end("train", step, logs)
                if num_iters is not None and step + 1 >= num_iters:
                    break
                if getattr(cbs, "stop_training", False):
                    stop = True
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          num_workers=num_workers)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbs.on_epoch_end(epoch, logs)
            if getattr(cbs, "stop_training", False):
                stop = True
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
        cbs.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._to_loader(eval_data, batch_size, False, num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            x, y = batch[0], batch[1] if len(batch) > 1 else None
            loss, _ = self.eval_batch(x, y)
            if loss is not None:
                losses.append(float(loss.item()))
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            logs.update(dict(zip(names, vals)))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._to_loader(test_data, batch_size, False, num_workers)
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(x).numpy())
        if stack_outputs:
            return [np.concatenate(outs, axis=0)]
        return [outs]

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    # -- persistence ------------------------------------------------------
    def save(self, path, training=True):
        from .. import framework
        framework.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework
        state = framework.load(path + ".pdparams")
        self.network.set_state_dict(state)
        import os
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(framework.load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
