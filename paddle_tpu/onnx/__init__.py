"""paddle.onnx parity surface (reference: python/paddle/onnx/export —
delegates to the external paddle2onnx package).

On this stack the deployment IR is StableHLO, not ONNX: export() lowers
the model through the jit tracer and writes <path>.stablehlo next to the
jit.save artifacts (the portable compiler-facing program every XLA-based
runtime consumes). If a true ONNX file is required, convert the StableHLO
externally (e.g. onnx-mlir / ivy) — this environment vendors no converter,
exactly like the reference, which also needs the separate paddle2onnx
package."""
import os

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=None, **configs):
    from ..jit import save as jit_save
    if input_spec is None:
        raise ValueError("onnx.export needs input_spec to trace the model")
    jit_save(layer, path, input_spec=input_spec)
    hlo = path + ".stablehlo"
    if os.path.exists(hlo):
        return hlo
    raise RuntimeError("export failed: no StableHLO artifact was produced")
