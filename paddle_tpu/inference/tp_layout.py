"""Tensor-parallel serving layout: the PartitionSpec catalog + weight
repacking that shards `FusedMultiTransformerEngine`'s paged serving path
over a one-axis `tp` device mesh.

Megatron-style split (reference bar: the SpecLayout PartitionSpec
catalogs production TPU serving stacks keep next to their meshes):

  * QKV projection — COLUMN-parallel over attention heads: each device
    computes `num_heads/tp` query heads and `kv_heads/tp` KV heads from
    the full hidden state. The paged KV cache shards over the SAME
    kv-head axis, so every device appends into — and attends over —
    exactly the heads it projected: the ragged work-list kernel runs
    unchanged on a `kv_heads/tp`-head local cache shard, and per-device
    KV HBM drops by the TP factor.
  * attention out-projection — ROW-parallel: each device contracts its
    local heads' context rows against its `[H*D/tp, E]` weight rows and
    the partial sums reduce with ONE `psum` over `tp` per layer.
  * FFN up (ffn1) — column-parallel; FFN down (ffn2) — row-parallel
    with the layer's second `psum`.
  * embeddings / lm_head / norm scales and biases — replicated (they
    are small at serving shapes; the residual stream stays replicated,
    which is what keeps the host-side scheduler single-brain: it ships
    ONE slab and reads ONE sampled-token array back).

Packed layouts need ROW/COLUMN REORDERING before a contiguous
`PartitionSpec` split is meaningful:

  * The GQA-packed qkv weight `[H + 2G, D, E]` interleaves q-heads,
    then k-heads, then v-heads. A naive axis-0 split hands device 1 a
    mix of late q-heads and early k-heads. `repack_gqa_qkv` reorders
    rows so each device's contiguous block is itself a valid GQA
    packing `[H/tp + 2G/tp, D, E]`.
  * A *glu ffn1 weight `[E, 2F]` pairs activation column j with gate
    column j+F. A contiguous 2F/tp split breaks the pairing (device
    d's local `split(2)` would gate a-columns against the WRONG
    g-columns). `repack_glu_ffn1` reorders columns so each device's
    block is `[a_d | g_d]` — locally splittable, and its activation
    output lines up with ffn2's contiguous row shard.

Both repacks are pure permutations: `unpack_*` inverts them exactly,
and the single-chip result is reproduced token-for-token (pinned by
tests/test_serve_tp.py and the serve_bench --tp gate).
"""
from dataclasses import dataclass

import numpy as np

__all__ = ["ServeSpecLayout", "validate_tp", "repack_gqa_qkv",
           "unpack_gqa_qkv", "repack_glu_ffn1", "shard_serving_weights",
           "serving_weight_specs"]


@dataclass(frozen=True)
class ServeSpecLayout:
    """Canonical PartitionSpecs for the fused-transformer serving
    weights over a one-axis tensor-parallel mesh (SpecLayout shape:
    one method per parameter family, axis names are data)."""

    tp_axis: str = "tp"

    def _ps(self, *dims):
        from jax.sharding import PartitionSpec as P
        return P(*dims)

    def qkv(self, gqa_packed):
        """[H+2G, D, E] GQA packing shards rows (after repack_gqa_qkv);
        the MHA [3, H, D, E] layout shards the head axis directly."""
        if gqa_packed:
            return self._ps(self.tp_axis, None, None)
        return self._ps(None, self.tp_axis, None, None)

    def qkv_bias(self, gqa_packed):
        if gqa_packed:
            return self._ps(self.tp_axis, None)
        return self._ps(None, self.tp_axis, None)

    def out_proj(self):
        """[H*D, E] row-parallel: the layer's first psum."""
        return self._ps(self.tp_axis, None)

    def ffn1(self):
        """[E, F'] column-parallel (F' = 2F for *glu, repacked)."""
        return self._ps(None, self.tp_axis)

    def ffn1_bias(self):
        return self._ps(self.tp_axis)

    def ffn2(self):
        """[F, E] row-parallel: the layer's second psum."""
        return self._ps(self.tp_axis, None)

    def replicated(self):
        """Embeddings, lm_head, norm scales/biases, out-proj/ffn2
        biases (added AFTER the psum), rotary tables."""
        return self._ps()

    def kv_cache(self):
        """[2, KVH, NB, BS, D] per-layer paged cache: kv-heads over tp
        — each device owns KVH/tp heads of EVERY block, so the block
        allocator stays a single host-side brain while per-device
        cache HBM drops by the TP factor."""
        return self._ps(None, self.tp_axis)


def validate_tp(num_heads, kv_heads, dim_feedforward, tp):
    """The divisibility contract a head-sharded serving engine needs;
    raises with the exact failing axis so misconfiguration is a
    constructor error, not a mid-step reshape explosion."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    for what, n in (("num_heads", num_heads), ("kv_heads", kv_heads),
                    ("dim_feedforward", dim_feedforward)):
        if n % tp != 0:
            raise ValueError(
                f"tensor-parallel serving needs {what} ({n}) divisible "
                f"by tp ({tp}) — each device owns {what}/tp of them")
    return tp


def _gqa_row_order(num_q, num_kv, tp):
    """Row permutation for the [H+2G, D, E] packing: per-device blocks
    [q_d | k_d | v_d] so a contiguous axis-0 split is a valid local
    GQA packing."""
    hq, hk = num_q // tp, num_kv // tp
    order = []
    for d in range(tp):
        order.extend(range(d * hq, (d + 1) * hq))                 # q rows
        order.extend(range(num_q + d * hk, num_q + (d + 1) * hk))  # k rows
        order.extend(range(num_q + num_kv + d * hk,                # v rows
                           num_q + num_kv + (d + 1) * hk))
    return np.asarray(order)


def repack_gqa_qkv(w, num_q, num_kv, tp):
    """Reorder a GQA-packed qkv weight [H+2G, D, E] (or bias [H+2G, D])
    so each of tp contiguous row blocks is itself GQA-packed over the
    device's local heads."""
    order = _gqa_row_order(num_q, num_kv, tp)
    return np.asarray(w)[order]


def unpack_gqa_qkv(w, num_q, num_kv, tp):
    """Inverse permutation of repack_gqa_qkv (tests pin the round
    trip)."""
    order = _gqa_row_order(num_q, num_kv, tp)
    inv = np.empty_like(order)
    inv[order] = np.arange(order.shape[0])
    return np.asarray(w)[inv]


def _glu_col_order(two_f, tp):
    f = two_f // 2
    fl = f // tp
    order = []
    for d in range(tp):
        order.extend(range(d * fl, (d + 1) * fl))          # a-columns
        order.extend(range(f + d * fl, f + (d + 1) * fl))  # g-columns
    return np.asarray(order)


def repack_glu_ffn1(w, tp, axis=-1):
    """Reorder a *glu ffn1 weight's [E, 2F] columns (or bias [2F]) into
    per-device [a_d | g_d] blocks: the local `split(2, axis=-1)` then
    pairs activation column j with ITS gate column, and the local
    activation output is ffn2's contiguous row shard in order."""
    w = np.asarray(w)
    order = _glu_col_order(w.shape[axis], tp)
    return np.take(w, order, axis=axis)


def serving_weight_specs(weights, layout=None):
    """PartitionSpec pytree MIRRORING a FusedMultiTransformerEngine
    weight dict (same keys, lists stay lists): the `in_specs` side of
    the shard_map'd paged programs. `weights` may hold arrays or
    shapes; only the key set and list lengths matter."""
    layout = layout or ServeSpecLayout()
    # the engine stores GQA-packed qkv as [H+2G, D, E] (rank 3) and the
    # MHA layout as [3, H, D, E] (rank 4) — the spec follows the rank
    sample = weights["qkv_weights"][0]
    gqa_packed = len(getattr(sample, "shape", np.shape(sample))) == 3

    def per_layer(spec, n):
        return [spec] * n

    specs = {}
    for k, v in weights.items():
        if k == "qkv_weights":
            specs[k] = per_layer(layout.qkv(gqa_packed), len(v))
        elif k == "qkv_wscales":
            # weight-quant scales [ht, hd, 1]: per-ROW of the packed
            # qkv layout, so they repack + split with their projection
            specs[k] = per_layer(layout.qkv(gqa_packed), len(v))
        elif k == "qkv_biases":
            specs[k] = per_layer(layout.qkv_bias(gqa_packed), len(v))
        elif k == "linear_weights":
            specs[k] = per_layer(layout.out_proj(), len(v))
        elif k == "ffn1_weights":
            specs[k] = per_layer(layout.ffn1(), len(v))
        elif k == "ffn1_wscales":
            # [1, 2F] per-COLUMN scales: column-parallel like ffn1
            # (and glu-repacked with it)
            specs[k] = per_layer(layout.ffn1(), len(v))
        elif k == "ffn1_biases":
            specs[k] = per_layer(layout.ffn1_bias(), len(v))
        elif k == "ffn2_weights":
            specs[k] = per_layer(layout.ffn2(), len(v))
        elif isinstance(v, (list, tuple)):
            # norm scales/biases, linear/ffn2 biases (post-psum adds),
            # and the linear/ffn2 weight-quant scales ([1, E]: per-
            # OUTPUT-channel of a row-parallel matmul — replicated)
            specs[k] = per_layer(layout.replicated(), len(v))
        else:
            specs[k] = layout.replicated()   # embedding / lm_head / rope
    return specs


def shard_serving_weights(weights, mesh, num_q, num_kv, glu, tp,
                          layout=None):
    """Repack + device_put a FusedMultiTransformerEngine weight dict
    onto the tp mesh per the layout catalog. Returns (sharded weights,
    spec pytree). `weights` values are jax/numpy arrays (the engine
    already cast dtypes); repacking happens host-side on numpy views.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    layout = layout or ServeSpecLayout()
    sample = weights["qkv_weights"][0]
    gqa_packed = len(sample.shape) == 3
    repacked = {}
    for k, v in weights.items():
        if k in ("qkv_weights", "qkv_biases", "qkv_wscales") \
                and gqa_packed and tp > 1:
            repacked[k] = [repack_gqa_qkv(np.asarray(w), num_q, num_kv,
                                          tp) for w in v]
        elif k in ("ffn1_weights", "ffn1_biases", "ffn1_wscales") \
                and glu and tp > 1:
            repacked[k] = [repack_glu_ffn1(np.asarray(w), tp) for w in v]
        else:
            repacked[k] = v
    specs = serving_weight_specs(weights, layout=layout)

    def put(arr, spec):
        return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))

    sharded = {}
    for k, v in repacked.items():
        if isinstance(v, (list, tuple)):
            sharded[k] = [put(a, s) for a, s in zip(v, specs[k])]
        else:
            sharded[k] = put(v, specs[k])
    return sharded, specs
