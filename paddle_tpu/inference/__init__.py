"""paddle.inference parity: Config / create_predictor / Predictor.

Reference: paddle/fluid/inference/api/analysis_predictor.h:101 +
python/paddle/inference (SURVEY.md §2.11). The reference predictor loads a
program, runs ~300 IR fusion passes, plans memory reuse, and executes with
zero-copy IO handles. On TPU that whole pipeline IS XLA: load the
jit.save artifact, jit-compile the restored layer (AOT per input shape,
cached), and keep IO as device-resident arrays. Precision switches map to
dtype casts (bf16 is the TPU-native mode)."""
import os
import pickle

import numpy as np

__all__ = ["Config", "PrecisionType", "create_predictor", "Predictor"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class Config:
    """Mirror of paddle.inference.Config's commonly-used surface."""

    def __init__(self, prog_file=None, params_file=None):
        # accept a prefix ("model/infer"), a model dir, or explicit files
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_prefix = prog_file
        self.params_file = params_file
        self._precision = PrecisionType.Float32
        self._memory_optim = True
        self._glog_info = True
        self._device = None
        self._cache_dir = None

    # -- device / precision ------------------------------------------------
    def enable_tpu(self, precision=PrecisionType.Bfloat16):
        self._device = "tpu"
        self._precision = precision

    def enable_use_gpu(self, memory_pool_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        # source-compat shim: GPU requests run on whatever PJRT device exists
        self._device = "tpu"
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n):
        pass  # XLA owns threading

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def set_optim_cache_dir(self, d):
        self._cache_dir = d

    def precision(self):
        return self._precision


class _IOHandle:
    """Zero-copy tensor handle (reference ZeroCopyTensor): the array stays
    device-resident between copy_from_cpu and run."""

    def __init__(self, name):
        self.name = name
        self._array = None
        self._shape = None   # declared via reshape() before data arrives
                             # (the C-API contract: reshape then copy)

    def reshape(self, shape):
        self._shape = list(shape)
        if self._array is not None:
            self._array = self._array.reshape(shape)

    def copy_from_cpu(self, arr):
        import jax
        a = np.asarray(arr)
        if self._shape is not None and list(a.shape) != self._shape:
            a = a.reshape(self._shape)
        self._array = jax.device_put(a)

    def share_external_data(self, tensor):
        self._array = tensor.data if hasattr(tensor, "data") else tensor

    def copy_to_cpu(self):
        return np.asarray(self._array)

    def shape(self):
        if self._array is not None:
            return list(self._array.shape)
        return list(self._shape) if self._shape else []


class Predictor:
    def __init__(self, config: Config):
        from ..jit.io import load as jit_load
        self._config = config
        self._layer = jit_load(config.model_prefix)
        if hasattr(self._layer, "eval"):
            self._layer.eval()
        if config.precision() in (PrecisionType.Bfloat16,
                                  PrecisionType.Half) \
                and hasattr(self._layer, "to"):
            # cast params to the serving dtype (bf16: MXU-native)
            self._cast_params(config.precision())
        self._inputs = {}
        self._outputs = {}
        self._compiled = {}
        self._n_inputs = None

    def _cast_params(self, dtype):
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        for _, p in self._layer.named_parameters():
            if p.data.dtype == jnp.float32:
                p.data = p.data.astype(dtype)

    # -- IO handles (reference get_input_handle/get_output_handle) --------
    def get_input_names(self):
        if self._n_inputs is None:
            return ["x0"]
        return [f"x{i}" for i in range(self._n_inputs)]

    def get_output_names(self):
        return sorted(self._outputs.keys())

    def get_input_handle(self, name):
        return self._inputs.setdefault(name, _IOHandle(name))

    def get_output_handle(self, name):
        return self._outputs.setdefault(name, _IOHandle(name))

    # -- execution ---------------------------------------------------------
    def run(self, inputs=None):
        """Execute. Either positional `inputs` (list of numpy arrays —
        convenience path) or pre-filled input handles."""
        import jax
        from ..core.tensor import Tensor
        from ..jit.functional import state_arrays, pure_call

        if inputs is not None:
            for i, a in enumerate(inputs):
                self.get_input_handle(f"x{i}").copy_from_cpu(a)

        def _order(name):  # numeric order: x2 before x10
            return (0, int(name[1:])) if name[1:].isdigit() else (1, name)

        handles = [self._inputs[k] for k in sorted(self._inputs, key=_order)]
        empty = [h.name for h in handles if h._array is None]
        if empty:
            raise RuntimeError(
                f"input handles never filled: {empty} — call "
                "copy_from_cpu on every input before run()")
        arrays = [h._array for h in handles]
        self._n_inputs = len(arrays)
        if self._config.precision() in (PrecisionType.Bfloat16,
                                        PrecisionType.Half):
            import jax.numpy as jnp
            arrays = [a.astype(self._config.precision())
                      if a.dtype == jnp.float32 else a for a in arrays]

        key = tuple((a.shape, str(a.dtype)) for a in arrays)
        if key not in self._compiled:
            params, buffers = state_arrays(self._layer)
            # deliberate snapshot, NOT a self.* capture (GL108): the
            # layer is the static module SKELETON — every array it owns
            # (params AND buffers) flows through jit arguments below; a
            # live self._layer reference inside the jitted closure
            # would pin whatever the attribute pointed at when each
            # shape first compiled
            layer = self._layer

            def fn(params, buffers, *xs):
                return pure_call(layer, params, buffers, *xs)

            self._compiled[key] = (jax.jit(fn), params, buffers)
        fn, params, buffers = self._compiled[key]
        out = fn(params, buffers, *arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for i, o in enumerate(outs):
            self.get_output_handle(f"out{i}")._array = o
        return [np.asarray(o) for o in outs]

    def clone(self):
        return Predictor(self._config)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class DataType:
    """IO dtype enum (reference paddle.inference.DataType)."""
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType:
    """IO placement enum (reference paddle.inference.PlaceType)."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3
    TPU = 3


Tensor = _IOHandle  # reference exposes the IO handle type as inference.Tensor


class PredictorPool:
    """Fixed-size predictor pool (reference PredictorPool): each entry is a
    clone sharing the compiled executables."""

    def __init__(self, config, size=1):
        self._preds = [Predictor(config) for _ in range(size)]

    def retrieve(self, idx):
        return self._preds[idx]


class XpuConfig:
    """Accelerator sub-config placeholder (reference XpuConfig); TPU memory
    is managed by PJRT so fields are recorded but not enforced."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0


def get_version():
    from .. import __version__
    return __version__


def get_num_bytes_of_data_type(dtype):
    import numpy as np
    return np.dtype({"bfloat16": "uint16"}.get(dtype, dtype)).itemsize


def get_trt_compile_version():
    """No TensorRT on TPU — the XLA compiler fills that role."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision,
                               backend=None, keep_io_types=True,
                               black_list=None):
    """Re-save a jit.save artifact with params cast to the target precision
    (reference convert_to_mixed_precision pass)."""
    import pickle
    import numpy as np
    import ml_dtypes
    import os
    if isinstance(mixed_precision, str):
        key = mixed_precision.lower()
    else:  # PrecisionType enum/string constants
        key = str(mixed_precision).lower()
    target = {"float16": np.float16, "half": np.float16,
              "precisiontype.half": np.float16,
              "bfloat16": ml_dtypes.bfloat16}.get(key, ml_dtypes.bfloat16)
    with open(params_file, "rb") as f:
        state = pickle.load(f)

    def cast(v):
        a = np.asarray(v)
        return a.astype(target) if a.dtype == np.float32 else a
    state = {k: cast(v) for k, v in state.items()}
    os.makedirs(os.path.dirname(mixed_params_file) or ".", exist_ok=True)
    with open(mixed_params_file, "wb") as f:
        pickle.dump(state, f)
    if os.path.exists(model_file) and model_file != mixed_model_file:
        import shutil
        shutil.copy(model_file, mixed_model_file)


def _get_phi_kernel_name(op_name):
    """Kernel-name mapping probe (reference _get_phi_kernel_name); ops here
    map 1:1 to registry names."""
    return op_name


def _arg_signature(args, kwargs, static_argnums=()):
    """Hashable shape/dtype signature of a jitted call — the same
    information that keys jax's executable cache, computed host-side:
    array leaves collapse to (shape, dtype) so VALUES never over-key
    (a work list with different block ids is the same program), while
    STATIC args keep their values (they key the compile). Used by the
    dispatch wrappers to attribute cost analyses once per signature."""
    import jax

    static = {i: a for i, a in enumerate(args) if i in set(static_argnums)}
    dyn = tuple(a for i, a in enumerate(args) if i not in static)

    def freeze(x):
        leaves, treedef = jax.tree_util.tree_flatten(x)
        return (str(treedef), tuple(
            (tuple(l.shape), str(l.dtype))
            if hasattr(l, "shape") and hasattr(l, "dtype")
            else ("py", type(l).__name__) for l in leaves))

    def freeze_static(x):
        leaves, treedef = jax.tree_util.tree_flatten(x)
        return (str(treedef), tuple(leaves))

    return (freeze((dyn, kwargs or {})),
            tuple((i, freeze_static(a)) for i, a in sorted(static.items())))


# host-side fault-injection point (paddle_tpu/testing/faults.py): a
# per-program dispatch delay in seconds, applied on the HOST before the
# compiled call is enqueued. This is how the chaos harness makes a step
# "slow/stalled" deterministically without touching the device program
# — the delay lands inside the dispatch span, so the flight recorder
# and the dispatch_seconds{program} histogram see exactly what a real
# stall would look like. Empty in production; never consulted under a
# tracer (the wrapper is plain host code).
_dispatch_delay = {}


def set_dispatch_delay(program, delay_s):
    """Testing hook: stall `program`'s dispatches by `delay_s` host
    seconds (0/None clears). Returns the previous value so callers can
    restore — the fault injector scopes it per step."""
    prev = _dispatch_delay.get(program)
    if not delay_s:
        _dispatch_delay.pop(program, None)
    else:
        _dispatch_delay[program] = float(delay_s)
    return prev


def _dispatch_span(name, fn, static_argnums=()):
    """Host-side span around a compiled program's dispatch (tracing.py
    ring; perf_counter timebase). jax dispatch is async: the measured
    interval covers trace/lower/compile (first call per bucket — which
    is why `paged_step` spans make recompiles visible on the timeline)
    plus enqueue, NOT device completion. The wrapper is plain host code
    wrapping the jitted callable, so the record never runs under a
    tracer (the GL105 contract). The duration also lands in the
    `dispatch_seconds{program}` histogram so the windowed time-series
    layer (observability/timeseries.py) can answer "did DISPATCH get
    slower over the last N seconds" — the signal that separates a
    model-side regression from queueing in the SLO engine's view.

    When the cost catalog is enabled (observability/costs.py — opt-in:
    an analysis pays one extra backend compile), the FIRST call per
    arg signature additionally AOT-analyzes the program and lands its
    FLOPs/bytes/HBM in the catalog — the signature set mirrors jax's
    own executable-cache keys, so analyses happen exactly at the cache
    misses the compile watch sees, BEFORE the call so donated buffers
    are still alive for lowering."""
    import time as _time

    from ..observability import costs as _costs
    from ..observability import instrument as _instrument
    from ..observability import tracing as _tracing

    seen = set()
    seen_gen = [None]

    def call(*args, **kwargs):
        catalog = _costs.get_cost_catalog()
        if catalog.enabled:
            if seen_gen[0] != catalog.generation:
                # the catalog was reset: warm signatures must
                # re-attribute or the cleared gauges stay empty until
                # an unseen shape arrives (possibly never)
                seen.clear()
                seen_gen[0] = catalog.generation
            try:
                sig = _arg_signature(args, kwargs, static_argnums)
            except Exception:
                sig = None
            if sig is not None and sig not in seen:
                seen.add(sig)
                catalog.analyze_jitted(name, fn, args, kwargs,
                                       signature=f"sig{len(seen)}")
        t0 = _time.perf_counter()
        delay = _dispatch_delay.get(name)
        if delay:
            # injected stall (testing hook above): inside the span and
            # the histogram on purpose — evidence looks like the fault
            _time.sleep(delay)
        out = fn(*args, **kwargs)
        dur = _time.perf_counter() - t0
        _tracing.get_tracer().record_span(name, t0 * 1e6, dur * 1e6)
        _instrument.dispatch_seconds().labels(program=name).observe(dur)
        return out

    call.__wrapped__ = fn
    return call


__all__ += ["FusedMultiTransformerEngine", "set_dispatch_delay"]
__all__ += ["DataType", "PlaceType", "Tensor", "PredictorPool", "XpuConfig",
            "get_version", "get_num_bytes_of_data_type",
            "get_trt_compile_version", "get_trt_runtime_version",
            "convert_to_mixed_precision", "_get_phi_kernel_name"]


class FusedMultiTransformerEngine:
    """Serving engine over the fused_multi_transformer op (role of the
    reference's fused_multi_transformer-based inference stack:
    AnalysisPredictor + fused decoder passes). Holds per-layer weight lists
    + embedding/lm_head, compiles ONE prefill program and ONE decode-step
    program (caches donated, so XLA updates them in place in HBM), and
    serves greedy generation.

    weights: dict with keys matching fused_multi_transformer's list args
    (ln_scales, qkv_weights, ...), plus 'embedding' [V, E] and 'lm_head'
    [E, V]. All values may be paddle Tensors or jax arrays.

    ``tp > 1`` shards the PAGED serving path over a one-axis tensor-
    parallel device mesh (inference/tp_layout.py): qkv/ffn1 weights
    split column-wise (per-head / per-feature), out-proj/ffn2 split
    row-wise with one psum each per layer, and the paged KV cache —
    plus the ragged work-list kernel's grid — shards over KV HEADS, so
    per-device cache HBM drops by the TP factor. The three paged
    programs (`_paged_step`/`_paged_rewind`/`_paged_copy`) become
    shard_map'd mesh programs with the SAME host-facing signatures and
    compile-key treadmill: the host-side scheduler stays single-brain
    and drives the whole mesh with one dispatch per step. Requires
    `num_heads`, kv heads, and the FFN width all divisible by tp, and
    tp visible devices. The dense `generate()` path is deliberately
    NOT mesh-aware (serving runs through ContinuousBatchingEngine);
    token-exactness vs a single-chip engine is gated by
    tools/serve_bench --tp and tests/test_serve_tp.py.
    """

    def __init__(self, weights, num_heads, head_dim, max_seq_len=2048,
                 norm_type="layernorm", activation="gelu",
                 use_neox_rotary_style=False, dtype="bfloat16",
                 gqa_group_size=-1, weight_quant=None, tp=1,
                 kv_buffer_depth=None, autotune_cache=None):
        import jax
        import jax.numpy as jnp
        from ..incubate.nn.functional import fused_multi_transformer

        def arr(v):
            from ..core.tensor import Tensor as _T
            a = v.data if isinstance(v, _T) else jnp.asarray(v)
            return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) \
                else a

        self._w = {k: ([arr(x) for x in v] if isinstance(v, (list, tuple))
                       else arr(v)) for k, v in weights.items()}
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.max_seq_len = max_seq_len
        self._dtype = dtype
        self._n_layers = len(self._w["qkv_weights"])
        # GQA (reference fused_transformer.py:1009): kv heads < q heads;
        # the cache is allocated at the kv-head count
        self._gqa = gqa_group_size if gqa_group_size and gqa_group_size > 0 \
            else 0
        kw = dict(norm_type=norm_type, activation=activation,
                  use_neox_rotary_style=use_neox_rotary_style,
                  gqa_group_size=gqa_group_size)
        # tensor-parallel serving (tp_layout.py): weights repacked +
        # device_put onto a one-axis mesh, and the paged programs below
        # become shard_map'd mesh programs. paged_kw is the PER-DEVICE
        # view the shard_map body computes with: local head counts and
        # the two row-parallel psums per layer.
        self.tp = int(tp) if tp else 1
        if self.tp < 1:
            # reject at construction like the divisibility errors: a
            # negative width would serve single-chip while poisoning
            # every mesh-aware surface (healthz mesh.tp, per-device
            # gauges) downstream
            raise ValueError(f"tp must be >= 1, got {tp}")
        self._mesh = None
        self._w_specs = None
        paged_kw = kw
        if self.tp > 1:
            import numpy as _np
            from jax.sharding import Mesh
            from ..ops.pallas.paged_attention import kv_head_shard
            from .tp_layout import validate_tp
            kvh_n = self._gqa or num_heads
            ffn_dim = int(self._w["ffn2_weights"][0].shape[0])
            validate_tp(num_heads, kvh_n, ffn_dim, self.tp)
            kv_head_shard(kvh_n, self.tp)   # same grid on every device
            devs = jax.devices()
            if len(devs) < self.tp:
                raise ValueError(
                    f"tp={self.tp} needs {self.tp} devices, "
                    f"have {len(devs)}")
            self._mesh = Mesh(_np.array(devs[:self.tp]), ("tp",))
            paged_kw = dict(kw)
            if self._gqa:
                paged_kw["gqa_group_size"] = self._gqa // self.tp
            paged_kw["_tp_reduce"] = lambda x: jax.lax.psum(x, "tp")
            if weight_quant == "int4":
                # the row-parallel specs split the PACKED nibble axis
                # (lin [K/2, E] / ffn2 [F/2, E]): each device's
                # contiguous row span must cover whole (2i, 2i+1)
                # nibble pairs or its unpack reconstructs rows that
                # straddle the device boundary
                for what, n in (("num_heads*head_dim",
                                 num_heads * head_dim),
                                ("dim_feedforward", ffn_dim)):
                    if (n // self.tp) % 2 != 0:
                        raise ValueError(
                            f"int4 weight_quant with tp={self.tp} needs "
                            f"{what}/tp ({n}//{self.tp}) even — packed "
                            "int4 rows split in (2i, 2i+1) pairs")
        # weight-only quantized serving: pack the matmul weights at load
        # (int4 = half the int8 tier's weight HBM) and dequantize inside
        # the op, fused into the operand load
        self.weight_quant = weight_quant
        tp_dequant = None
        if weight_quant in ("int4", "int8"):
            # int4 on TPU: the Pallas weight-only GEMM FIRST
            # (ops/pallas/quant_matmul.py — streams the packed bytes,
            # unpacks in-registers; the XLA nibble-unpack path was the
            # round-4 0.41x regression, the kernel makes it 1.16x).
            # Packed weights REPLACE self._w's lists so they flow as
            # program ARGUMENTS (closure capture would inline ~350 MB of
            # constants into the compile payload). int8 stays on the XLA
            # dequant path (measured equal-or-better: XLA fuses the
            # int8->bf16 convert into the operand load). The Pallas GEMM
            # is single-chip only: under tp the XLA dequant path runs
            # per-device on the weight shards instead.
            mm = None
            if weight_quant == "int4" and self.tp == 1 \
                    and jax.devices()[0].platform == "tpu":
                try:
                    mm = self._build_quant_mm(weights, dtype)
                except ValueError:
                    mm = None  # indivisible shape: dequant fallback below
            if mm is not None:
                kw["_mm"] = mm
            else:
                import numpy as _np
                from ..incubate.nn.functional import (_unpack_int4,
                                                      quantize_int4)
                qscales = {}

                def _quant(kind, ws, axis):
                    packed, scs = [], []
                    for t in ws:
                        a = _np.asarray(t, _np.float32)
                        if weight_quant == "int4":
                            pk, sc = quantize_int4(a, axis=axis)
                        else:
                            m = _np.moveaxis(a, axis, -1)
                            sc = _np.abs(m).max(-1, keepdims=True) / 127.0 \
                                + 1e-9
                            pk = _np.clip(_np.round(m / sc), -127, 127
                                          ).astype(_np.int8)
                            pk = _np.moveaxis(pk, -1, axis)
                            sc = _np.moveaxis(sc, -1, axis)
                        packed.append(jnp.asarray(pk))
                        scs.append(jnp.asarray(sc))
                    qscales[kind] = scs
                    return packed

                # quantization happens GLOBALLY (pre-shard, from the
                # full weights) in every case — under tp the per-device
                # shards are then exact row/column slices of the SAME
                # packed values + scales the dense engine serves, which
                # is what makes quantized tensor-parallel serving
                # token-exact vs the dense weight_quant generate()
                self._w["qkv_weights"] = _quant(
                    "qkv", self._w["qkv_weights"], -1)
                self._w["linear_weights"] = _quant(
                    "lin", self._w["linear_weights"], 0)
                self._w["ffn1_weights"] = _quant(
                    "f1", self._w["ffn1_weights"], 0)
                self._w["ffn2_weights"] = _quant(
                    "f2", self._w["ffn2_weights"], 0)
                cdt = dtype
                if self.tp == 1:
                    def dq(w, kind, li):
                        sc = qscales[kind][li]
                        if weight_quant == "int4":
                            full = _unpack_int4(
                                w, axis=-1 if kind == "qkv" else 0)
                        else:
                            full = w
                        return (full.astype(jnp.float32) * sc).astype(cdt)

                    kw["_dequant"] = dq
                else:
                    # tensor-parallel: the scales become WEIGHTS —
                    # tp_layout shards each alongside its packed
                    # projection (qkv/ffn1 scales follow their repack +
                    # split; lin/ffn2 scales are per-OUTPUT-channel so
                    # they replicate) — and dequantization runs
                    # per-device at the top of the shard_map'd step
                    # body, reconstructing exactly this device's shard
                    # of the dense engine's dequantized weights
                    self._w["qkv_wscales"] = qscales["qkv"]
                    self._w["linear_wscales"] = qscales["lin"]
                    self._w["ffn1_wscales"] = qscales["f1"]
                    self._w["ffn2_wscales"] = qscales["f2"]
                    is4 = weight_quant == "int4"

                    def tp_dequant(w):
                        w = dict(w)
                        for key, skey, axis in (
                                ("qkv_weights", "qkv_wscales", -1),
                                ("linear_weights", "linear_wscales", 0),
                                ("ffn1_weights", "ffn1_wscales", 0),
                                ("ffn2_weights", "ffn2_wscales", 0)):
                            scs = w.pop(skey)
                            w[key] = [
                                ((_unpack_int4(p, axis=axis) if is4
                                  else p).astype(jnp.float32)
                                 * sc).astype(cdt)
                                for p, sc in zip(w[key], scs)]
                        return w
        if self.tp > 1:
            from .tp_layout import shard_serving_weights
            self._w, self._w_specs = shard_serving_weights(
                self._w, self._mesh, num_heads, kvh_n,
                activation.endswith("glu"), self.tp)
        # KV DMA pipeline depth for the ragged kernel: an explicit arg
        # wins, else the committed autotune cache's winner for this
        # engine's shape class, else the classic double buffer. Resolved
        # ONCE here (closure into the paged step) — zero per-step cost.
        from ..ops.pallas import autotune as _autotune
        self._autotune_cache = None if autotune_cache is None \
            else _autotune.load_serve_cache(autotune_cache)
        if kv_buffer_depth is None:
            kvh_l = self._gqa or num_heads
            cfg = _autotune.serve_winner_for_engine(
                self._autotune_cache, kvh_l, num_heads // kvh_l,
                head_dim, dtype) if self._autotune_cache else None
            kv_buffer_depth = cfg["buffer_depth"] if cfg else 2
        self.kv_buffer_depth = int(kv_buffer_depth)
        paged_kw["kv_buffer_depth"] = self.kv_buffer_depth

        def lists(w):
            def g(name):
                return w.get(name) or None
            return (w["ln_scales"], g("ln_biases"), w["qkv_weights"],
                    g("qkv_biases"), w["linear_weights"], g("linear_biases"),
                    w["ffn_ln_scales"], g("ffn_ln_biases"), w["ffn1_weights"],
                    g("ffn1_biases"), w["ffn2_weights"], g("ffn2_biases"))

        def select(logits, temp, topp, key):
            """Greedy when temp<=0, else temperature + nucleus (top-p)
            sampling (reference top_p_sampling op semantics) — all traced,
            so the whole sampled decode stays one device program."""
            import jax
            greedy = jnp.argmax(logits, -1)
            lg = logits.astype(jnp.float32) / jnp.maximum(temp, 1e-6)
            sl = jnp.flip(jnp.sort(lg, -1), -1)
            ps = jax.nn.softmax(sl, -1)
            csum = jnp.cumsum(ps, -1)
            # last sorted index whose PRECEDING mass is still < top_p
            k_idx = jnp.sum((csum - ps) < topp, -1) - 1
            thresh = jnp.take_along_axis(
                sl, jnp.maximum(k_idx, 0)[..., None], -1)
            filt = jnp.where(lg >= thresh, lg, -jnp.inf)
            samp = jax.random.categorical(key, filt, -1)
            return jnp.where(temp <= 0.0, greedy, samp)

        def prefill(w, caches, ids, temp, topp, key, lens=None):
            h = w["embedding"][ids]
            from ..core.tensor import Tensor
            cts = [Tensor(c) for c in caches]
            out = fused_multi_transformer(
                Tensor(h), *lists(w), cache_kvs=cts,
                seq_lens=None if lens is None else Tensor(lens),
                rotary_embs=w.get("rotary_embs"), **kw)
            if lens is None:
                logits = out.data[:, -1] @ w["lm_head"]
            else:
                # ragged prompts: each row's LAST VALID hidden state
                bidx = jnp.arange(out.data.shape[0])
                logits = out.data[bidx, lens - 1] @ w["lm_head"]
            return select(logits, temp, topp, key), [c.data for c in cts]

        def step(w, caches, tok, t, temp, topp, key, lens=None):
            h = w["embedding"][tok][:, None]
            from ..core.tensor import Tensor
            cts = [Tensor(c) for c in caches]
            out = fused_multi_transformer(
                Tensor(h), *lists(w), cache_kvs=cts,
                time_step=Tensor(t),
                seq_lens=None if lens is None else Tensor(lens),
                rotary_embs=w.get("rotary_embs"), **kw)
            logits = out.data[:, 0] @ w["lm_head"]
            return select(logits, temp, topp, key), [c.data for c in cts]

        def steps(w, caches, tok, t0, n, temp, topp, key, lens0=None):
            # whole decode loop as ONE device program (lax.scan): a
            # per-token jit call pays a host->device dispatch round trip
            # each step — through a tunnel that RTT dwarfs the step itself.
            # Ragged mode: per-sequence lengths ride the carry and advance
            # each step (the op's seq_lens contract)
            import jax

            def body(carry, i):
                tk, cs, ln = carry
                tk2, cs2 = step(w, cs, tk, t0 + i, temp, topp,
                                jax.random.fold_in(key, i), lens=ln)
                ln2 = None if ln is None else ln + 1
                return (tk2, cs2, ln2), tk2

            (_, caches_f, _), toks = jax.lax.scan(
                body, (tok, caches, lens0), jnp.arange(n))
            return toks, caches_f  # toks [n, B]

        def paged_step(w, caches, toks, qlens, sel, tables, lens, rwork,
                       rpack, temp, topp, key):
            """One continuous-batching step over the PAGED cache: toks
            [B, C] is each slot's token slab for this step — decode
            slots carry one token in column 0, prefill slots up to C
            prompt-chunk tokens — and qlens [B] says how many columns
            are valid per slot (0 parks the slot: nothing written,
            nothing sampled that matters). tables/lens are the host
            allocator's view BEFORE the step, rwork the flattened ragged
            work list (built host-side from lens + qlens with
            q_lens=qlens). Mixed-progress slots — some consuming whole
            prompt chunks, some deep into decode, some idle — all
            advance in this ONE compiled program; the bucketed
            (work-list length, chunk-width) pair is the only shape that
            varies step to step, so the program count stays
            O(log max_blocks * log chunk). Samples only the positions
            the caller will read — `sel` [B, W] holds per-slot slab
            column indices (the chunk-final position for prefill slots,
            the whole 1+K drafted span for speculative verification:
            column j's sample is the model's next-token choice after
            slab column j, exactly what greedy acceptance compares
            drafts against) — and returns [B, W] tokens. W is bounded
            by 1 + spec_k, NOT the chunk width, so a 256-token prefill
            chunk still pays for one lm_head position per slot.
            Padding columns of sel repeat a valid index; their samples
            are computed and ignored."""
            if tp_dequant is not None:
                # quantized tensor-parallel serving: reconstruct this
                # device's dense weight shards from the packed bytes +
                # scales (runs inside the shard_map body, on shards)
                w = tp_dequant(w)
            h = w["embedding"][toks]             # [B, C, E]
            from ..core.tensor import Tensor
            cts = [Tensor(c) for c in caches]
            out = fused_multi_transformer(
                Tensor(h), *lists(w), cache_kvs=cts,
                time_step=Tensor(jnp.zeros((), jnp.int32)),
                seq_lens=Tensor(lens), chunk_lens=Tensor(qlens),
                rotary_embs=w.get("rotary_embs"),
                block_tables=tables, ragged_work=rwork,
                ragged_pack=rpack, **paged_kw)
            bidx = jnp.arange(out.data.shape[0])
            picked = out.data[bidx[:, None], sel]        # [B, W, E]
            logits = picked @ w["lm_head"]               # [B, W, V]
            return select(logits, temp, topp, key), [c.data for c in cts]

        def paged_copy(caches, src_block, dst_block):
            """Duplicate one physical cache block across every layer in
            ONE jitted program — the serving engine's copy-on-write
            primitive (automatic prefix caching: a request appending
            into a block other requests still read writes into a
            private copy instead). Block ids are traced scalars, so one
            compile covers every (src, dst) pair ever copied."""
            from ..ops.pallas.paged_attention import copy_paged_kv_block
            out = []
            for c in caches:
                kc, vc = copy_paged_kv_block(c[0], c[1], src_block,
                                             dst_block)
                out.append(jnp.stack([kc, vc]))
            return out

        def paged_rewind(caches, tables, new_lens, old_lens, span):
            """Roll every layer's paged cache back from old_lens to
            new_lens (zero the rejected speculative span) in ONE jitted
            program; `span` is static, the serving engine passes its
            bucketed slab width so the compile keys stay on the same
            O(log chunk) treadmill as the step itself."""
            from ..ops.pallas.paged_attention import truncate_paged_kv_cache
            out = []
            for c in caches:
                kc, vc = truncate_paged_kv_cache(
                    c[0], c[1], tables, new_lens, old_lens, span)
                out.append(jnp.stack([kc, vc]))
            return out

        import jax
        self._prefill = jax.jit(prefill, donate_argnums=(1,))
        self._step = jax.jit(step, donate_argnums=(1,))
        self._steps = jax.jit(steps, static_argnums=(4,),
                              donate_argnums=(1,))
        # serving-path programs get host-side dispatch spans: the
        # continuous-batching engine's per-request lanes line up against
        # these on one chrome timeline (a slow step with a fat
        # `paged_step` span on its first bucket sighting = compile)
        if self.tp == 1:
            jit_paged_step = jax.jit(paged_step, static_argnums=(8,),
                                     donate_argnums=(1,))
            jit_paged_rewind = jax.jit(paged_rewind, static_argnums=(4,),
                                       donate_argnums=(0,))
            jit_paged_copy = jax.jit(paged_copy, donate_argnums=(0,))
        else:
            # mesh programs: the SAME paged bodies run per-device under
            # shard_map — weights arrive as their layout shards, the
            # caches as kv-head shards, every host-built array (slab,
            # sel, tables, lens, work list) replicated — and the
            # sampled tokens come back replicated, so the host reads
            # ONE array exactly as in the single-chip case. Static args
            # (rpack / rewind span) stay OUTSIDE the shard_map via
            # closure, keeping the bucketed compile-key treadmill
            # identical per mesh shape. check_vma=False: the per-layer
            # psums make the residual stream replicated by construction
            # (jax-0.4.x's replication checker cannot see through the
            # Pallas kernel).
            from ..framework.compat import resolve_shard_map
            from jax.sharding import PartitionSpec as _P
            _shard_map = resolve_shard_map()
            mesh = self._mesh
            w_specs = self._w_specs
            n_layers = self._n_layers
            cspecs = [_P(None, "tp")] * n_layers
            rep = _P()

            def paged_step_tp(w, caches, toks, qlens, sel, tables, lens,
                              rwork, rpack, temp, topp, key):
                def local(w, caches, toks, qlens, sel, tables, lens,
                          rwork, temp, topp, key):
                    return paged_step(w, caches, toks, qlens, sel,
                                      tables, lens, rwork, rpack, temp,
                                      topp, key)
                f = _shard_map(
                    local, mesh=mesh,
                    in_specs=(w_specs, cspecs, rep, rep, rep, rep, rep,
                              (rep,) * 9, rep, rep, rep),
                    out_specs=(rep, cspecs),
                    axis_names=("tp",), check_vma=False)
                return f(w, caches, toks, qlens, sel, tables, lens,
                         rwork, temp, topp, key)

            def paged_rewind_tp(caches, tables, new_lens, old_lens,
                                span):
                def local(caches, tables, new_lens, old_lens):
                    return paged_rewind(caches, tables, new_lens,
                                        old_lens, span)
                f = _shard_map(
                    local, mesh=mesh,
                    in_specs=(cspecs, rep, rep, rep), out_specs=cspecs,
                    axis_names=("tp",), check_vma=False)
                return f(caches, tables, new_lens, old_lens)

            def paged_copy_tp(caches, src_block, dst_block):
                f = _shard_map(
                    paged_copy, mesh=mesh,
                    in_specs=(cspecs, rep, rep), out_specs=cspecs,
                    axis_names=("tp",), check_vma=False)
                return f(caches, src_block, dst_block)

            jit_paged_step = jax.jit(paged_step_tp, static_argnums=(8,),
                                     donate_argnums=(1,))
            jit_paged_rewind = jax.jit(paged_rewind_tp,
                                       static_argnums=(4,),
                                       donate_argnums=(0,))
            jit_paged_copy = jax.jit(paged_copy_tp, donate_argnums=(0,))
        self._paged_step = _dispatch_span(
            "paged_step", jit_paged_step, static_argnums=(8,))
        self._paged_rewind = _dispatch_span(
            "paged_rewind", jit_paged_rewind, static_argnums=(4,))
        self._paged_copy = _dispatch_span("paged_copy", jit_paged_copy)

    def _build_quant_mm(self, weights, dtype):
        """Repack the projection weights into the Pallas kernel's int4
        K x N layout and REPLACE self._w's lists with them (they flow as
        program arguments); returns the _mm(z2d, w, kind, li) hook running
        the weight-only GEMM. Matrix forms (trans_qkvw layouts):
        qkv [ht, hd, E] -> [E, ht*hd]; lin [H*D, E]; ffn1 [E, 2F];
        ffn2 [F, E] — per-output-channel scales (small; closure-carried).
        int4-only: int8 serves from the XLA dequant path."""
        import numpy as _np
        import jax.numpy as jnp
        from ..core.tensor import Tensor as _T
        from ..ops.pallas.quant_matmul import (pack_int4_blocked,
                                               pick_block_n,
                                               weight_only_matmul)

        def matrix(kind, a):
            a = _np.asarray(a, _np.float32)
            if kind == "qkv":          # [ht, hd, E] -> [E, ht*hd]
                return a.reshape(-1, a.shape[-1]).T
            return a                   # already [K, N]

        qkv0 = _np.asarray(weights["qkv_weights"][0])
        qkv_out = tuple(qkv0.shape[:-1])   # (ht, hd) GQA / (3, H, D) MHA
        new_lists = {}
        scales = {}
        blocks = {}
        for kind, key in (("qkv", "qkv_weights"), ("lin", "linear_weights"),
                          ("f1", "ffn1_weights"), ("f2", "ffn2_weights")):
            packed_l, sc_l = [], []
            for t in weights[key]:
                w = matrix(kind, t.numpy() if isinstance(t, _T) else t)
                bn = pick_block_n(w.shape[1], "int4")
                if bn is None:
                    raise ValueError(f"{kind} N={w.shape[1]}: no legal "
                                     "kernel block")
                blocks[kind] = bn
                packed, sc = pack_int4_blocked(w, block_n=bn)
                packed_l.append(jnp.asarray(packed))
                sc_l.append(jnp.asarray(sc))
            new_lists[key] = packed_l
            scales[kind] = sc_l
        self._w.update(new_lists)

        def mm(z2d, w, kind, li):
            return weight_only_matmul(z2d.astype(dtype), w,
                                      scales[kind][li], quant="int4",
                                      block_n=blocks[kind],
                                      out_dtype=dtype)

        mm.qkv_out = qkv_out
        return mm

    def new_caches(self, batch_size, dtype=None):
        import jax.numpy as jnp
        dtype = dtype or self._dtype
        kvh = self._gqa or self._w["qkv_weights"][0].shape[1]
        return [jnp.zeros((2, batch_size, kvh, self.max_seq_len,
                           self.head_dim), dtype)
                for _ in range(self._n_layers)]

    def new_paged_caches(self, num_blocks, block_size, dtype=None):
        """Per-layer paged KV caches [2, KVH, num_blocks, block_size, D]
        for the continuous-batching serving path
        (incubate.nn.ContinuousBatchingEngine owns the block allocator
        that hands slices of these out to requests). Under tp > 1 each
        layer's cache is placed sharded over KV HEADS — the GLOBAL
        (logical) shape is unchanged, each device holds a
        [2, KVH/tp, num_blocks, block_size, D] shard, so the host-side
        allocator keeps one flat block-id space while per-device cache
        HBM is 1/tp of the single-chip figure."""
        import jax.numpy as jnp
        dtype = dtype or self._dtype
        kvh = self._gqa or self.num_heads
        if self.tp > 1:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            sh = NamedSharding(self._mesh, P(None, "tp"))
            return [jax.device_put(
                jnp.zeros((2, kvh, num_blocks, block_size,
                           self.head_dim), dtype), sh)
                for _ in range(self._n_layers)]
        return [jnp.zeros((2, kvh, num_blocks, block_size,
                           self.head_dim), dtype)
                for _ in range(self._n_layers)]

    # -- tensor-parallel accounting (host math; tp == 1 degenerates) ------
    def kv_device_block_bytes(self, block_size):
        """Bytes ONE allocator block occupies PER DEVICE across every
        layer's cache shard: L x 2(K,V) x KVH/tp x block_size x D x
        itemsize. The per-device KV high-water in bytes is
        `allocator.high_water * this` — the capacity win the TP gate
        asserts (1/tp of the single-chip figure for the same
        workload)."""
        import jax.numpy as jnp
        kvh = self._gqa or self.num_heads
        itemsize = jnp.dtype(self._dtype).itemsize
        return (self._n_layers * 2 * (kvh // self.tp)
                * int(block_size) * self.head_dim * itemsize)

    def tp_step_comm_bytes(self, batch, width):
        """Analytic per-step collective payload of the TP paged step:
        two row-parallel psums per layer, each reducing a
        [batch, width, E] partial activation — the aval math the
        serving loop hands the comm-task registry so
        `collective_bytes_total{op="psum",axis="tp"}` attributes the
        step's comms cost without a device round trip. 0 when tp == 1
        (no collectives in the program)."""
        if self.tp <= 1:
            return 0
        import jax.numpy as jnp
        e = int(self._w["embedding"].shape[1])
        itemsize = jnp.dtype(self._dtype).itemsize
        return 2 * self._n_layers * int(batch) * int(width) * e * itemsize

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_p=1.0, seed=None, prompt_lens=None):
        """Generation: greedy by default; temperature>0 enables
        temperature + nucleus sampling (reference top_p_sampling
        semantics), seeded for reproducibility. input_ids: [B, S] int
        array. Returns [B, N].

        prompt_lens (optional [B] ints): ragged-batch mode — input_ids is
        RIGHT-padded to a common width and each row's true prompt length
        is given here; every row prefills over its own length and decodes
        at its own cache slot / rotary position, reproducing its unpadded
        single-sequence generation exactly. Each length must satisfy
        0 < len <= input_ids.shape[1]."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        if self.tp > 1:
            raise NotImplementedError(
                "generate() serves the dense single-chip cache; a "
                "tensor-parallel engine serves through "
                "ContinuousBatchingEngine's paged path (token-exact vs "
                "a tp=1 engine's generate() — the serve_tp gate pins "
                "it). Build the reference engine with tp=1.")
        if seed is None:
            from ..core import random as _rng
            key = _rng.next_key()
        else:
            key = jax.random.PRNGKey(int(seed))
        temp = jnp.float32(temperature)
        topp = jnp.float32(top_p)
        ids = jnp.asarray(input_ids, jnp.int32)
        b, s = ids.shape
        if s + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_seq_len ({self.max_seq_len}); raise max_seq_len or "
                "shorten the request")
        caches = self.new_caches(b)
        kp, kd = jax.random.split(key)
        lens = None
        if prompt_lens is not None:
            lens_np = np.asarray(prompt_lens)
            if lens_np.shape != (b,):
                raise ValueError(
                    f"prompt_lens must be shape [{b}], got {lens_np.shape}")
            if (lens_np <= 0).any() or (lens_np > s).any():
                raise ValueError(
                    f"prompt_lens must be in (0, {s}] (the padded width); "
                    f"got {lens_np.tolist()}")
            lens = jnp.asarray(lens_np, jnp.int32)
        tok, caches = self._prefill(self._w, caches, ids, temp, topp, kp,
                                    lens)
        if max_new_tokens == 1:
            return np.asarray(tok)[:, None]
        # bucket the scanned step count to powers of two so varying request
        # lengths reuse a handful of compiled decode programs instead of
        # recompiling the whole stack per distinct n (overshoot tokens are
        # computed then dropped; the cache slots they touched are beyond
        # the returned horizon and rewritten by any later decode)
        need = max_new_tokens - 1
        bucket = 1
        while bucket < need:
            bucket *= 2
        bucket = min(bucket, self.max_seq_len - s)
        toks, caches = self._steps(self._w, caches, tok,
                                   jnp.asarray(s, jnp.int32), bucket,
                                   temp, topp, kd, lens)
        return np.concatenate([np.asarray(tok)[:, None],
                               np.asarray(toks).T[:, :need]], axis=1)
