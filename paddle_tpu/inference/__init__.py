"""paddle.inference parity: Config / create_predictor / Predictor.

Reference: paddle/fluid/inference/api/analysis_predictor.h:101 +
python/paddle/inference (SURVEY.md §2.11). The reference predictor loads a
program, runs ~300 IR fusion passes, plans memory reuse, and executes with
zero-copy IO handles. On TPU that whole pipeline IS XLA: load the
jit.save artifact, jit-compile the restored layer (AOT per input shape,
cached), and keep IO as device-resident arrays. Precision switches map to
dtype casts (bf16 is the TPU-native mode)."""
import os
import pickle

import numpy as np

__all__ = ["Config", "PrecisionType", "create_predictor", "Predictor"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class Config:
    """Mirror of paddle.inference.Config's commonly-used surface."""

    def __init__(self, prog_file=None, params_file=None):
        # accept a prefix ("model/infer"), a model dir, or explicit files
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_prefix = prog_file
        self.params_file = params_file
        self._precision = PrecisionType.Float32
        self._memory_optim = True
        self._glog_info = True
        self._device = None
        self._cache_dir = None

    # -- device / precision ------------------------------------------------
    def enable_tpu(self, precision=PrecisionType.Bfloat16):
        self._device = "tpu"
        self._precision = precision

    def enable_use_gpu(self, memory_pool_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        # source-compat shim: GPU requests run on whatever PJRT device exists
        self._device = "tpu"
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n):
        pass  # XLA owns threading

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def set_optim_cache_dir(self, d):
        self._cache_dir = d

    def precision(self):
        return self._precision


class _IOHandle:
    """Zero-copy tensor handle (reference ZeroCopyTensor): the array stays
    device-resident between copy_from_cpu and run."""

    def __init__(self, name):
        self.name = name
        self._array = None

    def reshape(self, shape):
        if self._array is not None:
            self._array = self._array.reshape(shape)

    def copy_from_cpu(self, arr):
        import jax
        self._array = jax.device_put(np.asarray(arr))

    def share_external_data(self, tensor):
        self._array = tensor.data if hasattr(tensor, "data") else tensor

    def copy_to_cpu(self):
        return np.asarray(self._array)

    def shape(self):
        return list(self._array.shape) if self._array is not None else []


class Predictor:
    def __init__(self, config: Config):
        from ..jit.io import load as jit_load
        self._config = config
        self._layer = jit_load(config.model_prefix)
        if hasattr(self._layer, "eval"):
            self._layer.eval()
        if config.precision() in (PrecisionType.Bfloat16,
                                  PrecisionType.Half) \
                and hasattr(self._layer, "to"):
            # cast params to the serving dtype (bf16: MXU-native)
            self._cast_params(config.precision())
        self._inputs = {}
        self._outputs = {}
        self._compiled = {}
        self._n_inputs = None

    def _cast_params(self, dtype):
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        for _, p in self._layer.named_parameters():
            if p.data.dtype == jnp.float32:
                p.data = p.data.astype(dtype)

    # -- IO handles (reference get_input_handle/get_output_handle) --------
    def get_input_names(self):
        if self._n_inputs is None:
            return ["x0"]
        return [f"x{i}" for i in range(self._n_inputs)]

    def get_output_names(self):
        return sorted(self._outputs.keys())

    def get_input_handle(self, name):
        return self._inputs.setdefault(name, _IOHandle(name))

    def get_output_handle(self, name):
        return self._outputs.setdefault(name, _IOHandle(name))

    # -- execution ---------------------------------------------------------
    def run(self, inputs=None):
        """Execute. Either positional `inputs` (list of numpy arrays —
        convenience path) or pre-filled input handles."""
        import jax
        from ..core.tensor import Tensor
        from ..jit.functional import state_arrays, pure_call

        if inputs is not None:
            for i, a in enumerate(inputs):
                self.get_input_handle(f"x{i}").copy_from_cpu(a)

        def _order(name):  # numeric order: x2 before x10
            return (0, int(name[1:])) if name[1:].isdigit() else (1, name)

        handles = [self._inputs[k] for k in sorted(self._inputs, key=_order)]
        empty = [h.name for h in handles if h._array is None]
        if empty:
            raise RuntimeError(
                f"input handles never filled: {empty} — call "
                "copy_from_cpu on every input before run()")
        arrays = [h._array for h in handles]
        self._n_inputs = len(arrays)
        if self._config.precision() in (PrecisionType.Bfloat16,
                                        PrecisionType.Half):
            import jax.numpy as jnp
            arrays = [a.astype(self._config.precision())
                      if a.dtype == jnp.float32 else a for a in arrays]

        key = tuple((a.shape, str(a.dtype)) for a in arrays)
        if key not in self._compiled:
            params, buffers = state_arrays(self._layer)

            def fn(params, *xs):
                return pure_call(self._layer, params, buffers, *xs)

            self._compiled[key] = (jax.jit(fn), params)
        fn, params = self._compiled[key]
        out = fn(params, *arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for i, o in enumerate(outs):
            self.get_output_handle(f"out{i}")._array = o
        return [np.asarray(o) for o in outs]

    def clone(self):
        return Predictor(self._config)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
