"""Fleet observability plane: per-rank mirroring, cross-process
aggregation, straggler detection.

Every surface under this package (registry, span ring, SLO engine,
time series) is process-local; the fleet shapes the ROADMAP asks for —
multi-replica serving behind one gateway, MegaScale-style cross-host
straggler detection — need ONE view over N processes before any
routing, drain, or scaling decision can be proven. Three pieces, same
design constraints as the rest of the package (stdlib-only at import,
lock-protected, host-side only):

* ``RankExporter`` — cadence-gated atomic mirror of a rank's registry
  snapshot + span-ring digest into a shared fleet directory
  (``fleet_rank_<r>.json``, latest-wins, tmp+rename so a reader never
  sees a torn file) plus a merged manifest. Every export is stamped
  with the fleet run id, rank, world size, a sequence number, and a
  clock block (wall / monotonic / perf_counter-µs) — the
  monotonic-clock offset marker: rank clocks are NOT comparable, so
  consumers window each rank on its own timebase and the stamp is
  what lets a viewer line lanes up. Re-arm-adoptable like the flight
  recorder: a restarted rank adopts its previous file's sequence
  instead of rewinding it.
* aggregation — :func:`merge_snapshots` folds N rank snapshots into a
  fleet view: counters sum EXACTLY (deterministic ascending-rank
  order, so the result is bit-equal to a plain sum of the per-rank
  values), fixed-bucket histograms merge EXACTLY (element-wise bucket
  sums — fleet p50/p95/p99 are real quantiles over the pooled
  observations, not averages of per-rank quantiles), and gauges keep
  their per-rank values under an appended ``rank`` label (bounded by
  world size at construction — GL112-safe) plus min/max/mean/skew
  rollups. :func:`snapshot_from_prometheus` rebuilds the same
  snapshot shape from a live ``/metrics`` scrape (de-cumulating the
  bucket series), so aggregation works from scrapes and mirror files
  alike.
* ``FleetMonitor`` — per-rank :class:`~.timeseries.TimeSeries` rings
  fed by ``ingest()`` (seq-gated), comparing each rank's windowed
  ``dispatch_seconds`` / step-phase / collective-wait mean against the
  median of the OTHER ranks with a MAD margin (leave-one-out: a
  straggler must not pollute its own baseline; ``min_count`` guards
  thin windows). A breach lands
  ``fleet_straggler_breaches_total{check}``, a ``fleet_straggler``
  timeline event on the merged span ring, and a ``fleet_straggler``
  flight dump naming the offending rank with both witness bucket
  distributions. The monitor's own ring carries every rank's spans on
  namespaced lanes (``r<rank>:<request>``), so the dump replays in
  tools/request_trace.py as merged per-rank lanes.

Verified by ``tools/fleet_obs.py --check tools/fleet_obs.json`` (real
multi-process ranks, healthy + injected-delay legs) and stdlib-only by
``tools/metrics_snapshot.py --selfcheck`` under a blocked jax import.
"""
import json
import math
import os
import threading
import time

from .exporters import parse_prometheus
from .metrics import get_registry
from .timeseries import TimeSeries
from .tracing import FlightRecorder, SpanRecorder, get_tracer

__all__ = [
    "RankExporter", "FleetMonitor", "merge_snapshots",
    "snapshot_from_prometheus", "merged_quantile", "gauge_rollups",
    "load_rank_snapshot", "load_fleet_manifest", "discover_snapshots",
    "SNAPSHOT_SCHEMA", "FLEET_MANIFEST_SCHEMA", "FLEET_VIEW_SCHEMA",
    "FLEET_MANIFEST_NAME", "STRAGGLER_REASON", "DEFAULT_CHECKS",
]

SNAPSHOT_SCHEMA = "paddle_tpu.fleet_rank_snapshot/1"
FLEET_MANIFEST_SCHEMA = "paddle_tpu.fleet_manifest/1"
FLEET_VIEW_SCHEMA = "paddle_tpu.fleet_view/1"
FLEET_MANIFEST_NAME = "fleet_manifest.json"
STRAGGLER_REASON = "fleet_straggler"

# (check label, histogram family) pairs the monitor compares across
# ranks by default: serving dispatch, the train step-phase split, and
# eager collective wait — the distributions a straggling host skews
# first. Families a workload never records simply contribute no window.
DEFAULT_CHECKS = (
    ("dispatch", "dispatch_seconds"),
    ("step", "train_step_seconds"),
    ("data_wait", "train_data_wait_seconds"),
    ("host", "train_host_seconds"),
    ("collective", "collective_seconds"),
)


def _rank_file(rank):
    return f"fleet_rank_{int(rank)}.json"


# -- loaders (stdlib-only validation, load_dump contract) -------------------

def load_rank_snapshot(path):
    """Load + schema-validate one rank mirror file. Raises ValueError
    on anything that is not a v1 rank snapshot, OSError when absent."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) \
            or data.get("schema") != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"{path}: not a {SNAPSHOT_SCHEMA} snapshot (schema="
            f"{data.get('schema') if isinstance(data, dict) else None!r})")
    missing = {"run_id", "rank", "world_size", "seq", "clock",
               "metrics", "spans"} - set(data)
    if missing:
        raise ValueError(f"{path}: snapshot missing keys "
                         f"{sorted(missing)}")
    clock = data["clock"]
    if not isinstance(clock, dict) \
            or not {"time", "monotonic", "perf_us"} <= set(clock):
        raise ValueError(f"{path}: malformed clock block")
    if not isinstance(data["metrics"], dict) \
            or not isinstance(data["spans"], list):
        raise ValueError(f"{path}: metrics/spans have the wrong shape")
    return data


def load_fleet_manifest(fleet_dir):
    """Load + schema-validate ``<dir>/fleet_manifest.json``."""
    path = os.path.join(str(fleet_dir), FLEET_MANIFEST_NAME)
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) \
            or data.get("schema") != FLEET_MANIFEST_SCHEMA:
        raise ValueError(
            f"{path}: not a {FLEET_MANIFEST_SCHEMA} manifest (schema="
            f"{data.get('schema') if isinstance(data, dict) else None!r})")
    ranks = data.get("ranks")
    if not isinstance(ranks, dict):
        raise ValueError(f"{path}: manifest ranks is not a dict")
    for r, e in ranks.items():
        if not {"file", "seq", "time"} <= set(e):
            raise ValueError(
                f"{path}: manifest entry for rank {r} malformed: "
                f"{sorted(e)}")
    return data


def discover_snapshots(fleet_dir, run_id=None):
    """Latest snapshot per rank from a fleet dir: {rank: payload}.
    The manifest indexes the dir but the rank FILES are the authority
    (a lost manifest race self-heals on the next export); unreadable
    or foreign-run files are skipped, never fatal — aggregation must
    work mid-rollout."""
    out = {}
    try:
        names = os.listdir(str(fleet_dir))
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith("fleet_rank_")
                and name.endswith(".json")):
            continue
        try:
            snap = load_rank_snapshot(os.path.join(str(fleet_dir), name))
        except (OSError, ValueError):
            continue
        if run_id is not None and snap["run_id"] != run_id:
            continue
        out[int(snap["rank"])] = snap
    return out


# -- per-rank mirroring -----------------------------------------------------

class RankExporter:
    """Cadence-gated atomic mirror of this rank's registry + span ring.

    ``maybe_export()`` is the hot-path entry: a monotonic-clock gate,
    then one snapshot + one tmp-write + one rename. The span digest
    carries only spans that CLOSED since the previous export (disjoint
    windows on the perf_counter watermark), so a monitor ingesting
    every seq sees each span exactly once. Re-arming a restarted rank
    over an existing fleet dir adopts its previous file's seq — the
    flight-recorder adoption idiom — so downstream seq-gating keeps
    rejecting stale files instead of re-ingesting history."""

    def __init__(self, fleet_dir, rank, world_size, run_id="fleet",
                 interval_s=2.0, registry=None, recorder=None):
        rank, world_size = int(rank), int(world_size)
        if not 0 <= rank < world_size:
            raise ValueError(
                f"rank {rank} outside world of {world_size}")
        self.fleet_dir = str(fleet_dir)
        self.rank = rank
        self.world_size = world_size
        self.run_id = str(run_id)
        self.interval_s = float(interval_s)
        self.registry = registry      # None = the process registry
        self.recorder = recorder      # None = the process tracer
        self.path = os.path.join(self.fleet_dir, _rank_file(rank))
        self._lock = threading.Lock()
        self._last_export = None      # monotonic of last export
        self._span_wm_us = 0.0        # perf_counter watermark (µs)
        self._seq = 0
        self.exports = 0              # files written this process
        # adoption: continue the previous incarnation's sequence
        try:
            prev = load_rank_snapshot(self.path)
            if prev["run_id"] == self.run_id \
                    and int(prev["rank"]) == rank:
                self._seq = int(prev["seq"])
        except (OSError, ValueError):
            pass

    @property
    def seq(self):
        with self._lock:
            return self._seq

    def maybe_export(self, now=None):
        """Export when the cadence elapsed; returns the path or None.
        Cheap when gated: one monotonic read under the lock."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._last_export is not None \
                    and now - self._last_export < self.interval_s:
                return None
        return self.export(now=now)

    def export(self, now=None):
        """Unconditional export; returns the written path."""
        now = time.monotonic() if now is None else float(now)
        reg = self.registry if self.registry is not None \
            else get_registry()
        # `is not None`: an EMPTY custom ring is falsy (__len__)
        rec = self.recorder if self.recorder is not None \
            else get_tracer()
        now_us = time.perf_counter() * 1e6
        with self._lock:
            wm = self._span_wm_us
            self._span_wm_us = now_us
            self._seq += 1
            seq = self._seq
            self._last_export = now
        spans = [s for s in rec.spans(since_us=wm)
                 if wm < s["ts_us"] + s["dur_us"] <= now_us]
        payload = {
            "schema": SNAPSHOT_SCHEMA,
            "run_id": self.run_id,
            "rank": self.rank,
            "world_size": self.world_size,
            "seq": seq,
            "clock": {"time": time.time(), "monotonic": now,
                      "perf_us": now_us},
            "metrics": reg.snapshot(),
            "spans": spans,
            "span_stats": {"exported": len(spans),
                           "recorded_total": rec.recorded_total},
        }
        os.makedirs(self.fleet_dir, exist_ok=True)
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, self.path)
        self._update_manifest(seq, payload["clock"]["time"])
        with self._lock:
            self.exports += 1
        return self.path

    def _update_manifest(self, seq, wall):
        """Read-merge-write the shared manifest (this rank's entry
        only). Concurrent ranks can lose each other's update between
        read and rename; every export rewrites, so the index converges
        — and discover_snapshots treats the rank FILES as authority,
        the manifest as an index."""
        path = os.path.join(self.fleet_dir, FLEET_MANIFEST_NAME)
        try:
            data = load_fleet_manifest(self.fleet_dir)
        except (OSError, ValueError):
            data = {"schema": FLEET_MANIFEST_SCHEMA, "ranks": {}}
        data["run_id"] = self.run_id
        data["world_size"] = self.world_size
        data["ranks"][str(self.rank)] = {
            "file": _rank_file(self.rank), "seq": seq, "time": wall}
        tmp = path + f".tmp.{self.rank}"
        try:
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            pass                    # the next export retries


# -- aggregation ------------------------------------------------------------

def _skew(vals):
    """Fisher-Pearson moment skewness; 0.0 for degenerate spreads."""
    n = len(vals)
    mean = sum(vals) / n
    m2 = sum((v - mean) ** 2 for v in vals) / n
    if m2 <= 0:
        return 0.0
    m3 = sum((v - mean) ** 3 for v in vals) / n
    return m3 / m2 ** 1.5


def merge_snapshots(snapshots):
    """Fold N rank snapshots into one fleet view.

    `snapshots`: rank-snapshot payloads (RankExporter files), raw
    ``registry.snapshot()`` dicts, or a {rank: payload} mapping.
    Ranks merge in ascending order, so float counter sums are
    DETERMINISTIC — bit-equal to summing the per-rank values in the
    same order (what the gate asserts). Histograms must agree on
    bucket edges (they are fixed at construction; a mismatch means
    two code versions and raises). Gauges keep every per-rank value
    under an appended ``rank`` label — bounded by world size, never
    by traffic (GL112) — with min/max/mean/skew rollups per child.
    """
    if isinstance(snapshots, dict):
        items = [snapshots[k] for k in sorted(snapshots)]
    else:
        items = list(snapshots)
        items.sort(key=lambda p: int(p.get("rank", 0))
                   if isinstance(p.get("rank", 0), (int, float, str))
                   else 0)
    ranks, metrics_by_rank, world = [], [], 0
    for i, p in enumerate(items):
        if "metrics" in p and "kind" not in p.get("metrics", {}):
            rank = int(p.get("rank", i))
            world = max(world, int(p.get("world_size", 0)))
            metrics = p["metrics"]
        else:
            rank, metrics = i, p
        if rank in ranks:
            raise ValueError(f"duplicate rank {rank} in merge")
        ranks.append(rank)
        metrics_by_rank.append(metrics)
    world = max(world, len(ranks))
    merged, rollups = {}, {}
    timeline = {"samples": 0, "capacity": 0, "dropped": 0}
    per_rank_gauges = {}        # (family, ckey) -> [(rank, value)]
    for rank, metrics in zip(ranks, metrics_by_rank):
        for name, fam in metrics.items():
            kind = fam.get("kind")
            if name == "_timeline" or kind == "meta":
                for k in timeline:
                    timeline[k] += int(fam.get(k, 0) or 0)
                continue
            if kind not in ("counter", "gauge", "histogram"):
                continue
            ent = merged.get(name)
            if ent is None:
                ent = merged[name] = {
                    "kind": kind, "help": fam.get("help", ""),
                    "labelnames": list(fam.get("labelnames") or ()),
                    "children": {}}
                if kind == "histogram":
                    ent["buckets"] = list(fam["buckets"])
                if kind == "gauge":
                    ent["labelnames"] = ent["labelnames"] + ["rank"]
            else:
                if ent["kind"] != kind:
                    raise ValueError(
                        f"{name}: kind mismatch across ranks "
                        f"({ent['kind']} vs {kind})")
                if kind == "histogram" \
                        and list(fam["buckets"]) != ent["buckets"]:
                    raise ValueError(
                        f"{name}: bucket edges differ across ranks — "
                        "exact histogram merge needs one edge set")
            for ckey, child in (fam.get("children") or {}).items():
                if kind == "counter":
                    c = ent["children"].setdefault(ckey, {"value": 0.0})
                    c["value"] += float(child["value"])
                elif kind == "histogram":
                    c = ent["children"].get(ckey)
                    counts = child["bucket_counts"]
                    if c is None:
                        ent["children"][ckey] = {
                            "bucket_counts": list(counts),
                            "sum": float(child["sum"]),
                            "count": int(child["count"])}
                    else:
                        if len(counts) != len(c["bucket_counts"]):
                            raise ValueError(
                                f"{name}: bucket count width differs")
                        c["bucket_counts"] = [
                            a + b for a, b in
                            zip(c["bucket_counts"], counts)]
                        c["sum"] += float(child["sum"])
                        c["count"] += int(child["count"])
                else:           # gauge: per-rank child + rollup input
                    nkey = f"{ckey},{rank}" if ckey else str(rank)
                    ent["children"][nkey] = {
                        "value": float(child["value"])}
                    per_rank_gauges.setdefault(
                        (name, ckey), []).append(
                            (rank, float(child["value"])))
    for (name, ckey), pairs in sorted(per_rank_gauges.items()):
        vals = [v for _, v in pairs]
        rollups.setdefault(name, {})[ckey] = {
            "min": min(vals), "max": max(vals),
            "mean": sum(vals) / len(vals), "skew": _skew(vals),
            "per_rank": {str(r): v for r, v in pairs}}
    merged["_timeline"] = {"kind": "meta", "help": "",
                           "labelnames": [], "children": {},
                           **timeline}
    return {"schema": FLEET_VIEW_SCHEMA, "ranks": ranks,
            "world_size": world, "metrics": merged, "gauges": rollups}


def gauge_rollups(view, name):
    """{child-key: {min,max,mean,skew,per_rank}} for one gauge family
    of a merged view (empty when the family recorded nothing)."""
    return view.get("gauges", {}).get(name, {})


def _hist_quantile(buckets, counts, q, total=None):
    """Histogram.quantile interpolation on explicit edges + counts."""
    if not 0 <= q <= 1:
        raise ValueError(f"quantile {q} outside [0, 1]")
    total = sum(counts) if total is None else total
    if total == 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if cum + c >= rank and c:
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i] if i < len(buckets) else buckets[-1]
            if hi <= lo:
                return hi
            return lo + (hi - lo) * max(0.0, rank - cum) / c
        cum += c
    return buckets[-1]


def merged_quantile(view, name, q, child=""):
    """Real fleet quantile of a merged histogram family: interpolated
    over the POOLED bucket counts (Histogram.quantile semantics), not
    an average of per-rank quantiles. None when the family/child is
    absent or empty."""
    fam = view.get("metrics", {}).get(name)
    if fam is None or fam.get("kind") != "histogram":
        return None
    c = fam["children"].get(child)
    if c is None:
        return None
    return _hist_quantile(fam["buckets"], c["bucket_counts"], q,
                          total=c["count"])


def snapshot_from_prometheus(text):
    """Rebuild a ``registry.snapshot()``-shaped dict from text
    exposition 0.0.4 (the inverse of exporters.to_prometheus via
    parse_prometheus), de-cumulating histogram bucket series — so
    merge_snapshots works identically from live /metrics scrapes and
    mirror files. Untyped families parse as gauges."""
    snap = {}
    for fname, fam in parse_prometheus(text).items():
        samples = fam["samples"]
        if not samples:
            continue
        kind = fam["kind"] or "gauge"
        if kind == "histogram":
            labelnames, per = None, {}
            for sname, labels, val in samples:
                base = {k: v for k, v in labels.items() if k != "le"}
                if labelnames is None:
                    labelnames = list(base)
                key = ",".join(str(base.get(k, "")) for k in labelnames)
                d = per.setdefault(key, {"cum": [], "sum": 0.0,
                                         "count": 0})
                if sname.endswith("_bucket"):
                    d["cum"].append((float(labels.get("le", "inf")
                                           if labels.get("le") not in
                                           ("+Inf", None)
                                           else math.inf), val))
                elif sname.endswith("_sum"):
                    d["sum"] = float(val)
                elif sname.endswith("_count"):
                    d["count"] = int(val)
            edges = None
            children = {}
            for key, d in per.items():
                cum = sorted(d["cum"])
                child_edges = [e for e, _ in cum if math.isfinite(e)]
                if edges is None:
                    edges = child_edges
                elif child_edges != edges:
                    raise ValueError(
                        f"{fname}: bucket edges differ across children")
                counts, prev = [], 0.0
                for _, c in cum:
                    if c < prev:
                        raise ValueError(
                            f"{fname}: non-monotonic bucket series")
                    counts.append(int(c - prev))
                    prev = c
                if len(counts) == len(edges):    # no +Inf series seen
                    counts.append(max(0, d["count"] - int(prev)))
                children[key] = {"bucket_counts": counts,
                                 "sum": d["sum"], "count": d["count"]}
            if not edges:
                continue
            snap[fname] = {"kind": "histogram",
                           "help": fam["help"] or "",
                           "labelnames": labelnames or [],
                           "buckets": edges, "children": children}
        else:
            labelnames, children = None, {}
            for _, labels, val in samples:
                if labelnames is None:
                    labelnames = list(labels)
                key = ",".join(str(labels.get(k, ""))
                               for k in labelnames)
                children[key] = {"value": float(val)}
            snap[fname] = {"kind": kind, "help": fam["help"] or "",
                           "labelnames": labelnames or [],
                           "children": children}
    return snap


# -- straggler detection ----------------------------------------------------

def _median(vals):
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


class FleetMonitor:
    """Cross-rank straggler detector on per-rank TimeSeries rings.

    ``ingest()`` replays one rank snapshot into that rank's ring
    (seq-gated: stale or replayed files are dropped) and copies its
    span digest onto the monitor's merged ring under a namespaced lane
    (``r<rank>:<request>``; rankless spans land on ``r<rank>``).
    ``check()`` compares, per configured (check, histogram-family)
    pair, each rank's windowed mean against the median of the OTHER
    ranks (leave-one-out — the straggler must not drag its own
    baseline) with margin ``mad_factor * MAD(others) + abs_floor_s``
    and a ``min_count`` guard against thin windows. Every rank's
    window is computed on ITS OWN monotonic clock (the snapshot's
    clock stamp) — fleet clocks are never mixed. A breach lands the
    ``fleet_straggler_breaches_total{check}`` counter, a timeline
    event, and a ``fleet_straggler`` flight dump carrying both
    witness distributions (the rank's and the pooled others')."""

    def __init__(self, fleet_dir=None, run_id=None, window_s=30.0,
                 min_count=8, mad_factor=4.0, abs_floor_s=0.005,
                 checks=None, registry=None, recorder=None,
                 flight=None, dump_dir=None, min_interval_s=30.0,
                 capacity=512):
        self.fleet_dir = None if fleet_dir is None else str(fleet_dir)
        self.run_id = run_id
        self.window_s = float(window_s)
        self.min_count = int(min_count)
        self.mad_factor = float(mad_factor)
        self.abs_floor_s = float(abs_floor_s)
        self.checks = tuple(checks if checks is not None
                            else DEFAULT_CHECKS)
        self.registry = registry      # None = the process registry
        self.capacity = int(capacity)
        self.recorder = recorder if recorder is not None \
            else SpanRecorder(capacity=16384)
        # the dump covers the WHOLE merged ring, not a perf_counter
        # window: ingested spans keep their remote rank's perf_counter
        # timebase, so windowing them by the monitor's local clock
        # would silently drop skewed lanes — the bounded ring is the
        # retention here
        self.flight = flight if flight is not None else FlightRecorder(
            recorder=self.recorder, window_s=1e9,
            min_interval_s=min_interval_s)
        if dump_dir is not None:
            self.flight.arm(dump_dir)
        self._lock = threading.RLock()
        self._series = {}             # rank -> TimeSeries
        self._seen = {}               # rank -> last ingested seq
        self._now = {}                # rank -> latest monotonic stamp
        self._clock = {}              # rank -> latest clock block
        self._last_stats = {}         # check -> {rank: mean_s}
        self.breaches = []            # breach dicts, oldest first

    # -- ingestion --------------------------------------------------------
    def ingest(self, payload, validate=True):
        """Feed one rank snapshot; returns True when it advanced the
        rank's ring (False = stale/duplicate seq)."""
        if validate and payload.get("schema") != SNAPSHOT_SCHEMA:
            raise ValueError(
                f"not a {SNAPSHOT_SCHEMA} payload: "
                f"{payload.get('schema')!r}")
        rank = int(payload["rank"])
        seq = int(payload["seq"])
        with self._lock:
            if self._seen.get(rank, 0) >= seq:
                return False
            self._seen[rank] = seq
            series = self._series.get(rank)
            if series is None:
                series = self._series[rank] = TimeSeries(
                    capacity=self.capacity)
            ts = float(payload["clock"]["monotonic"])
            self._now[rank] = ts
            self._clock[rank] = dict(payload["clock"])
        series.sample_snapshot(payload["metrics"], now=ts)
        lane = f"r{rank}"
        for s in payload.get("spans", ()):
            req = s.get("request")
            args = {k: v for k, v in (s.get("args") or {}).items()
                    if k not in ("name", "start_us", "dur_us",
                                 "request")}
            self.recorder.record_span(
                s["name"], s["ts_us"], s["dur_us"],
                request=f"{lane}:{req}" if req is not None else lane,
                **args)
        return True

    def poll(self, now=None):
        """Discover + ingest anything new in the fleet dir, then run
        the checks; returns the fresh breaches."""
        if self.fleet_dir is not None:
            for rank in sorted(
                    snaps := discover_snapshots(self.fleet_dir,
                                                run_id=self.run_id)):
                self.ingest(snaps[rank], validate=False)
        return self.check(now=now)

    # -- checking ---------------------------------------------------------
    def _rank_window(self, series, family, now_r):
        """Pooled (counts, sum, count) of every child of `family` in
        the rank's window; None when nothing (or mixed widths)."""
        tot_counts, tot_sum, tot_n, edges = None, 0.0, 0, None
        for sname in series.names():
            if sname != family \
                    and not sname.startswith(family + "{"):
                continue
            if series.kind(sname) != "histogram":
                continue
            d = series.hist_delta(sname, self.window_s, now=now_r)
            if d is None:
                continue
            counts, s, n = d
            if tot_counts is None:
                tot_counts = list(counts)
                edges = series._buckets.get(sname)
            elif len(counts) == len(tot_counts):
                tot_counts = [a + b for a, b in
                              zip(tot_counts, counts)]
            else:
                continue        # foreign bucket width: skip the child
            tot_sum += s
            tot_n += n
        if tot_counts is None or tot_n == 0:
            return None
        return tot_counts, tot_sum, tot_n, edges

    def check(self, now=None):
        """Run every configured check over the current rings; returns
        the list of fresh breach dicts (empty = healthy). `now` is
        accepted for API symmetry but each rank is windowed on its own
        snapshot clock — fleet clocks are never comparable."""
        del now
        fresh = []
        with self._lock:
            series_by_rank = dict(self._series)
            now_by_rank = dict(self._now)
        for check_name, family in self.checks:
            stats = {}          # rank -> (mean, counts, n, edges)
            for rank in sorted(series_by_rank):
                w = self._rank_window(series_by_rank[rank], family,
                                      now_by_rank[rank])
                if w is None:
                    continue
                counts, total, n, edges = w
                if n < self.min_count:
                    continue
                stats[rank] = (total / n, counts, n, edges)
            self._last_stats[check_name] = {
                r: v[0] for r, v in stats.items()}
            if len(stats) < 2:
                continue
            for rank in sorted(stats):
                mean, counts, n, edges = stats[rank]
                others = [stats[r][0] for r in stats if r != rank]
                med = _median(others)
                mad = _median([abs(m - med) for m in others])
                margin = self.mad_factor * mad + self.abs_floor_s
                if mean <= med + margin:
                    continue
                fleet_counts = None
                for r in sorted(stats):
                    if r == rank:
                        continue
                    c = stats[r][1]
                    if fleet_counts is None:
                        fleet_counts = list(c)
                    elif len(c) == len(fleet_counts):
                        fleet_counts = [a + b for a, b in
                                        zip(fleet_counts, c)]
                breach = {"check": check_name, "family": family,
                          "rank": rank, "mean_s": mean,
                          "median_s": med, "mad_s": mad,
                          "margin_s": margin, "count": n,
                          "window_s": self.window_s}
                fresh.append(breach)
                self._land(breach, counts, fleet_counts, edges)
        with self._lock:
            self.breaches.extend(fresh)
        return fresh

    def _land(self, breach, rank_counts, fleet_counts, edges):
        reg = self.registry if self.registry is not None \
            else get_registry()
        reg.counter(
            "fleet_straggler_breaches_total",
            help="cross-rank straggler breaches by check",
            labels=("check",)).labels(check=breach["check"]).inc()
        lane = f"r{breach['rank']}"
        self.recorder.event(
            STRAGGLER_REASON, request=lane, check=breach["check"],
            rank=breach["rank"], mean_s=breach["mean_s"],
            median_s=breach["median_s"])
        # witness distributions ride as JSON strings: flight context
        # is scalar/string-only by the _clean_value contract
        self.flight.trigger(
            STRAGGLER_REASON, request=lane, rank=breach["rank"],
            check=breach["check"], family=breach["family"],
            mean_s=breach["mean_s"], median_s=breach["median_s"],
            mad_s=breach["mad_s"], margin_s=breach["margin_s"],
            window_s=breach["window_s"], count=breach["count"],
            rank_hist=json.dumps(rank_counts),
            fleet_hist=json.dumps(fleet_counts),
            hist_buckets=json.dumps(list(edges or ())))

    # -- reporting --------------------------------------------------------
    def fleet_view(self):
        """merge_snapshots over the latest files in the fleet dir."""
        if self.fleet_dir is None:
            raise ValueError("monitor has no fleet_dir")
        return merge_snapshots(
            discover_snapshots(self.fleet_dir, run_id=self.run_id))

    def summary(self):
        """json-safe monitor state for dashboards/reports."""
        with self._lock:
            return {
                "ranks": sorted(self._seen),
                "seqs": {str(r): s for r, s in
                         sorted(self._seen.items())},
                "clocks": {str(r): dict(c) for r, c in
                           sorted(self._clock.items())},
                "checks": {c: {str(r): m for r, m in sorted(st.items())}
                           for c, st in
                           sorted(self._last_stats.items())},
                "breaches_total": len(self.breaches),
                "breaches": [dict(b) for b in self.breaches[-32:]],
            }
