"""Per-request lifecycle tracing + anomaly flight recorder.

PR 3's metrics answer "how is the fleet doing"; this module answers
"why was THIS request slow". With chunked prefill, token budgets,
speculative decode, and KV rewind all interleaving on one compiled
step, a p99 outlier can be queue starvation, a budget-starved prefill,
a spec-rejection storm, an alloc-failure stall, or a post-warmup
recompile — aggregates cannot tell those apart; request-scoped spans
can.

Three pieces, same design constraints as metrics.py (host-side only,
stdlib-only at import, lock-protected):

* ``SpanRecorder`` — a bounded ring of spans ``(ts_us, dur_us, name,
  request, args)``. Recording is a deque append under one lock; the
  ring is sized so "always on" costs nothing measurable next to a
  serving step, and old spans fall off the back instead of growing
  memory. The same ``float()`` tracer guard as the metrics registry
  protects every recorded value: a span recorded under a jax trace
  raises at trace time (graftlint GL105 enforces the same contract
  statically, now covering ``tracing.*`` too).
* chrome export — ``chrome_span_events()`` renders the ring as
  ``"ph": "X"`` duration events on per-request lanes; the profiler
  merges them into its host-range + metric-counter stream so one
  chrome://tracing view shows what every request was doing inside
  every step.
* ``FlightRecorder`` — the ring always runs; when an anomaly trigger
  fires (KV alloc failure, post-warmup bucket recompile, rolling-TPOT
  SLO breach, comm-watchdog stall) it dumps the last ``window_s``
  seconds of spans plus a full metrics snapshot to a timestamped JSON
  file. Disarmed by default (``arm(dir)`` opts in) and rate-limited
  per reason, so a repeating anomaly produces evidence, not a disk
  full of identical dumps. ``tools/request_trace.py`` replays a dump
  as per-request timelines; ``tools/metrics_snapshot.py --selfcheck``
  validates the schema stdlib-only.

Span timebase is ``time.perf_counter()`` microseconds — the same clock
the profiler stamps host ranges and the metrics timeline with, so all
three streams land on one chrome timeline without skew.
"""
import collections
import contextlib
import json
import os
import tempfile
import threading
import time

from .metrics import _host_float, get_registry

__all__ = [
    "SpanRecorder", "FlightRecorder", "get_tracer", "get_flight_recorder",
    "span", "event", "chrome_span_events", "request_summary",
    "requests_seen", "load_dump", "write_dump", "arm_default",
    "load_manifest", "operator_abort_dump", "run_with_abort_evidence",
    "DUMP_SCHEMA", "MANIFEST_SCHEMA", "MANIFEST_NAME",
]

DUMP_SCHEMA = "paddle_tpu.flight_recorder/1"
MANIFEST_SCHEMA = "paddle_tpu.flight_manifest/1"
MANIFEST_NAME = "flightrec_manifest.json"

# server-entrypoint retention defaults (arm_default): bounded enough
# that a long-running server can never fill a disk with evidence, deep
# enough that a p99 incident's dump survives until a human looks
DEFAULT_MAX_DUMPS = 16
DEFAULT_MAX_BYTES = 64 * 1024 * 1024

# chrome tids for span lanes: far away from thread idents (host ranges)
# and from tid 0 (metric counters) so per-request lanes group cleanly
_LANE_TID_BASE = 1000000


def _clean_value(v, what):
    """Host-scalar guard for span args: strings/None pass through, bools
    stay bools, everything else must coerce through float() — a jax
    tracer fails that coercion, which is the runtime half of the
    host-side-only contract (static half: graftlint GL105). Integral
    floats come back as ints so dumps stay readable."""
    if v is None or isinstance(v, (str, bool)):
        return v
    f = _host_float(v, what)
    return int(f) if f.is_integer() else f


class SpanRecorder:
    """Bounded, lock-protected ring of host-side spans."""

    def __init__(self, capacity=8192):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._lock = threading.RLock()
        self._spans = collections.deque(maxlen=self.capacity)
        self.enabled = True
        self.recorded_total = 0     # appends ever (ring drops the oldest)

    # -- recording --------------------------------------------------------
    def record_span(self, name, start_us, dur_us, request=None, **args):
        """Append one span. `start_us`/`dur_us` are perf_counter
        microseconds; `request` is the request id the span belongs to
        (None = engine lane); `args` are small host scalars/strings."""
        if not self.enabled:
            return
        what = f"span {name!r}"
        start_us = _host_float(start_us, what)
        dur_us = _host_float(dur_us, what)
        if request is not None and not isinstance(request, str):
            request = _clean_value(request, what)
        if args:
            args = {k: _clean_value(v, f"{what} arg {k!r}")
                    for k, v in args.items()}
        with self._lock:
            self._spans.append((start_us, dur_us, str(name), request,
                                args or None))
            self.recorded_total += 1

    def event(self, name, request=None, **args):
        """Zero-duration instant (first token, stall, trigger, ...)."""
        self.record_span(name, time.perf_counter() * 1e6, 0.0,
                         request=request, **args)

    @contextlib.contextmanager
    def span(self, name, request=None, **args):
        """Context manager measuring the enclosed host interval."""
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.record_span(name, t0 * 1e6,
                             (time.perf_counter() - t0) * 1e6,
                             request=request, **args)

    # -- reading ----------------------------------------------------------
    def spans(self, since_us=None, until_us=None, request=None):
        """Snapshot as json-friendly dicts, oldest first. The window
        keeps any span that OVERLAPS it: `since_us` tests the span's
        END (a 60s queue_wait that closes inside a 30s flight-recorder
        window is exactly the outlier evidence the dump exists for),
        `until_us` its start. `request` filters one lane."""
        with self._lock:
            raw = list(self._spans)
        out = []
        for ts, dur, name, req, args in raw:
            if since_us is not None and ts + dur < since_us:
                continue
            if until_us is not None and ts > until_us:
                continue
            if request is not None and req != request:
                continue
            out.append({"name": name, "ts_us": ts, "dur_us": dur,
                        "request": req, "args": args or {}})
        return out

    def __len__(self):
        with self._lock:
            return len(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()


_tracer = SpanRecorder()


def get_tracer():
    """The process-wide span ring every instrumented surface records
    into (the serving engine, the paged-step dispatch wrappers, ...)."""
    return _tracer


def span(name, request=None, **args):
    """`with tracing.span("prefill_chunk", request=rid, width=64):` on
    the process-wide recorder."""
    return _tracer.span(name, request=request, **args)


def event(name, request=None, **args):
    _tracer.event(name, request=request, **args)


# -- chrome export ---------------------------------------------------------

def chrome_span_events(recorder=None, pid=None, since_us=None,
                       until_us=None):
    """The ring as chrome-trace ``"ph": "X"`` duration events, one lane
    (tid) per request id plus lane 0 for engine-scope spans, with
    ``"M"`` thread_name metadata naming each lane — merged by
    Profiler._export_chrome into the host-range + counter stream. Every
    event carries the full profiler key set (the export contract)."""
    recorder = recorder if recorder is not None else get_tracer()
    if pid is None:
        pid = os.getpid()
    lanes = {}      # request id -> lane tid, by first appearance

    def lane(req):
        if req is None:
            return _LANE_TID_BASE
        t = lanes.get(req)
        if t is None:
            t = lanes[req] = _LANE_TID_BASE + 1 + len(lanes)
        return t

    events = []
    for s in recorder.spans(since_us=since_us, until_us=until_us):
        args = dict(s["args"])
        if s["request"] is not None:
            args["request"] = s["request"]
        events.append({"name": s["name"], "ph": "X", "ts": s["ts_us"],
                       "dur": s["dur_us"], "pid": pid,
                       "tid": lane(s["request"]), "cat": "request",
                       "args": args})
    meta = [{"name": "thread_name", "ph": "M", "ts": 0, "dur": 0,
             "pid": pid, "tid": _LANE_TID_BASE, "cat": "request",
             "args": {"name": "serve engine"}}] if events else []
    for req, tid in lanes.items():
        meta.append({"name": "thread_name", "ph": "M", "ts": 0, "dur": 0,
                     "pid": pid, "tid": tid, "cat": "request",
                     "args": {"name": f"request {req}"}})
    return meta + events


# -- per-request summary ---------------------------------------------------

def requests_seen(recorder=None, limit=None):
    """Distinct request ids in the span ring, oldest-first (the
    gateway's /requests listing: the ring is the one place every
    request's lifecycle already lands, live and retired alike, so the
    control plane needs no second registry). `limit` keeps the NEWEST
    n ids."""
    rec = recorder if recorder is not None else get_tracer()
    seen = {}
    for s in rec.spans():
        r = s["request"]
        if r is not None and r not in seen:
            seen[r] = True
    ids = list(seen)
    if limit is not None and len(ids) > limit:
        ids = ids[-int(limit):]
    return ids


def request_summary(request, spans=None, recorder=None):
    """`request.explain()`-style digest of one request's lifecycle from
    its spans: queue wait, TTFT, chunk grants (granted vs requested),
    stalls, decode/spec accounting, effective TPOT. Works on live rings
    and on flight-recorder dumps (pass the dump's `spans` list)."""
    if spans is None:
        spans = (recorder if recorder is not None
                 else get_tracer()).spans(request=request)
    else:
        spans = [s for s in spans if s.get("request") == request]
    out = {
        "request": request,
        "spans": len(spans),
        "queue_wait_s": None,
        "ttft_s": None,
        "tpot_s": None,
        "prefill_chunks": [],
        "prompt_tokens": None,
        "generated_tokens": None,
        "decode_steps": 0,
        "cached_prefix_tokens": 0,
        "stalls": {"budget": 0, "alloc": 0, "admit_blocked": 0,
                   "cache_pending": 0},
        "spec": {"drafted": 0, "accepted": 0, "accept_rate": None,
                 "rewinds": 0, "blocks_freed": 0},
        "preemptions": 0,
        "status": None,
        "retired": False,
    }
    first_token_us = None
    last_decode_end_us = None
    tokens_after_first = 0
    for s in spans:
        name, args = s["name"], s.get("args") or {}
        if name == "submit":
            out["prompt_tokens"] = args.get("prompt_tokens")
        elif name == "queue_wait":
            out["queue_wait_s"] = s["dur_us"] / 1e6
        elif name == "prefill_chunk":
            out["prefill_chunks"].append(
                {"granted": args.get("granted"),
                 "requested": args.get("requested")})
        elif name == "first_token":
            first_token_us = s["ts_us"]
            out["ttft_s"] = args.get("ttft_s")
        elif name == "decode":
            out["decode_steps"] += 1
            emitted = args.get("emitted", 1) or 0
            tokens_after_first += emitted
            last_decode_end_us = s["ts_us"] + s["dur_us"]
            out["spec"]["drafted"] += args.get("drafted", 0) or 0
            out["spec"]["accepted"] += args.get("accepted", 0) or 0
            if (args.get("drafted", 0) or 0) > (args.get("accepted", 0)
                                                or 0):
                out["spec"]["rewinds"] += 1
            out["spec"]["blocks_freed"] += args.get("blocks_freed", 0) or 0
        elif name == "cache_hit":
            # cumulative in the event args: the last one wins (a prefix
            # may extend across steps as the wavefront catches up)
            out["cached_prefix_tokens"] = args.get(
                "total", out["cached_prefix_tokens"])
        elif name == "stall_budget":
            out["stalls"]["budget"] += 1
        elif name == "stall_alloc":
            out["stalls"]["alloc"] += 1
        elif name == "stall_cache_pending":
            out["stalls"]["cache_pending"] += 1
        elif name == "admit_blocked":
            out["stalls"]["admit_blocked"] += 1
        elif name == "preempt":
            out["preemptions"] += 1
            out["status"] = "preempted"
        elif name in ("cancel", "shed", "reject", "deadline_exceeded",
                      "request_failed"):
            # terminal lifecycle events carry the structured status the
            # engine recorded on the request (the retire event below
            # overrides for requests that went on to finish)
            out["status"] = args.get("status", out["status"])
        elif name == "retire":
            out["retired"] = True
            out["generated_tokens"] = args.get("generated")
            out["status"] = args.get("status", "finished")
    if out["spec"]["drafted"]:
        out["spec"]["accept_rate"] = round(
            out["spec"]["accepted"] / out["spec"]["drafted"], 4)
    if (first_token_us is not None and last_decode_end_us is not None
            and tokens_after_first > 0):
        out["tpot_s"] = ((last_decode_end_us - first_token_us) / 1e6
                         / tokens_after_first)
    return out


# -- flight recorder -------------------------------------------------------

class FlightRecorder:
    """Anomaly-triggered dump of the span ring + a metrics snapshot.

    The ring records continuously and cheaply; `trigger(reason, ...)`
    writes the last `window_s` seconds of spans and the full metrics
    registry to ``<dir>/flightrec_<reason>_<ms>_<seq>.json`` — but only
    when armed (`arm(dir)`), and at most once per `min_interval_s` per
    reason, so a repeating anomaly leaves evidence without flooding the
    disk. `max_dumps`/`max_bytes` bound the dir regardless (oldest-first
    rotation + a manifest index — the long-running-server policy
    `arm_default()` turns on). Triggers wired in today:
    ``kv_alloc_failure`` (now a PER-REQUEST failure: fired only when no
    preemptible victim exists), ``preemption`` (a victim's KV went back
    to blocks and the request re-queued), ``post_warmup_recompile`` and
    ``tpot_slo_breach`` (incubate/nn/continuous_batching.py),
    ``slo_burn_rate`` (observability/slo.py burn-rate breaches),
    ``hbm_pressure`` (observability/memory.py),
    ``comm_watchdog_stall`` (distributed/comm_watchdog.py),
    ``operator_abort`` (serve entrypoints catching
    KeyboardInterrupt/SystemExit — `operator_abort_dump()`), plus
    ``manual`` via write_dump()."""

    def __init__(self, recorder=None, window_s=30.0, min_interval_s=2.0,
                 max_dumps=None, max_bytes=None):
        self.recorder = recorder    # None = the process-wide tracer
        self.window_s = float(window_s)
        self.min_interval_s = float(min_interval_s)
        self.max_dumps = max_dumps      # retention: None = unbounded
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._dir = None
        self._last = {}             # reason -> last-dump perf_counter
        self._seq = 0
        self._manifest = []         # retained-dump index (armed dir)
        self.evicted_total = 0      # dumps rotated out by retention
        self.dumps = []             # paths written this process

    @property
    def armed(self):
        return self._dir is not None

    def arm(self, out_dir, window_s=None, min_interval_s=None,
            max_dumps=None, max_bytes=None):
        """Start dumping into `out_dir` (created on first dump).
        `max_dumps`/`max_bytes` bound the dir: after every write the
        oldest dumps rotate out until both limits hold (the newest dump
        always survives), and a manifest index
        (``<dir>/flightrec_manifest.json``) lists what is retained. An
        existing manifest in the dir is adopted, so a restarted server
        keeps rotating the same evidence window instead of leaking the
        previous process's dumps."""
        # validate BEFORE mutating: a rejected arm() must leave the
        # recorder exactly as it was (a caught ValueError must not leave
        # it armed with an evict-everything quota)
        if max_dumps is not None and int(max_dumps) < 1:
            raise ValueError("max_dumps must be >= 1")
        if max_bytes is not None and int(max_bytes) < 1:
            raise ValueError("max_bytes must be >= 1")
        # the manifest ADOPTION (a disk read) happens before the lock:
        # arming must not stall a concurrent trigger/record behind file
        # IO (GL115) — only the state flip is serialized. On a re-arm of
        # the dir we are ALREADY rotating, the in-memory manifest is the
        # authority (a trigger may have retained a dump between the read
        # above and the lock below — adopting the disk copy would orphan
        # it); the disk read only seeds a dir this process isn't
        # tracking yet.
        adopted = self._adopt_manifest(str(out_dir))
        with self._lock:
            rearming_same_dir = self._dir == str(out_dir)
            self._dir = str(out_dir)
            if window_s is not None:
                self.window_s = float(window_s)
            if min_interval_s is not None:
                self.min_interval_s = float(min_interval_s)
            if max_dumps is not None:
                self.max_dumps = int(max_dumps)
            if max_bytes is not None:
                self.max_bytes = int(max_bytes)
            if not rearming_same_dir:
                self._manifest = adopted
        return self

    def disarm(self):
        with self._lock:
            self._dir = None
            self._manifest = []

    # -- retention --------------------------------------------------------
    @staticmethod
    def _adopt_manifest(out_dir):
        """Entries of an existing manifest whose files still exist —
        a fresh arm() of a dir a previous process dumped into continues
        its rotation instead of orphaning the old files."""
        try:
            data = load_manifest(out_dir)
        except (OSError, ValueError):
            return []
        return [dict(e) for e in data["dumps"]
                if os.path.exists(os.path.join(out_dir, e["file"]))]

    def _retain(self, path, reason, rec):
        """Register a just-written dump in the manifest and rotate the
        oldest dumps out until max_dumps/max_bytes hold (newest always
        kept). Runs on the serving thread: any OSError is recorded, not
        raised — retention must never take down the step."""
        out_dir = os.path.dirname(path)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        evicted = []
        io_error = None
        # the manifest WRITE stays under the lock too: two concurrent
        # triggers (serving thread + watchdog thread, different reasons
        # so both clear the cooldown) must not interleave state-mutate
        # and write — the loser would persist a stale manifest missing
        # the winner's dump, orphaning it from rotation forever
        with self._lock:
            if self._dir is None or out_dir != self._dir:
                return              # explicit-path dump: not managed
            self._manifest.append(
                {"file": os.path.basename(path), "reason": str(reason),
                 "time": time.time(), "bytes": int(size),
                 "seq": self._seq})
            total = sum(e["bytes"] for e in self._manifest)
            while len(self._manifest) > 1 and (
                    (self.max_dumps is not None
                     and len(self._manifest) > self.max_dumps)
                    or (self.max_bytes is not None
                        and total > self.max_bytes)):
                e = self._manifest.pop(0)   # oldest-first
                total -= e["bytes"]
                evicted.append(e)
                self.evicted_total += 1
            manifest = [dict(e) for e in self._manifest]
            try:
                # deliberate GL115 exceptions: eviction + manifest write
                # stay under the lock so two concurrent triggers can't
                # interleave state-mutate and write (the loser would
                # persist a stale manifest orphaning the winner's dump
                # from rotation); _retain runs per-DUMP, not per-step
                for e in evicted:
                    try:
                        os.remove(os.path.join(out_dir, e["file"]))  # graftlint: disable=GL115 - manifest-rotation atomicity (see above)
                    except FileNotFoundError:
                        pass
                tmp = os.path.join(out_dir, MANIFEST_NAME + ".tmp")
                with open(tmp, "w") as f:  # graftlint: disable=GL115 - same manifest-atomicity exception
                    json.dump({"schema": MANIFEST_SCHEMA,  # graftlint: disable=GL115 - same manifest-atomicity exception
                               "evicted_total": self.evicted_total,
                               "dumps": manifest}, f, indent=1)
                os.replace(tmp, os.path.join(out_dir, MANIFEST_NAME))  # graftlint: disable=GL115 - same manifest-atomicity exception
            except OSError as e:
                io_error = e
        if io_error is not None:
            rec.event("flight_retention_failed", error=str(io_error))
            return
        if evicted:
            rec.event("flight_dump_evicted", count=len(evicted))
            get_registry().counter(
                "flight_recorder_dumps_evicted_total",
                help="dumps rotated out by the retention policy").inc(
                    len(evicted))

    def retained(self):
        """Manifest snapshot: the dumps retention currently keeps."""
        with self._lock:
            return [dict(e) for e in self._manifest]

    def trigger(self, reason, request=None, **context):
        """Record the anomaly; write a dump when armed + off cooldown.
        Returns the dump path, or None when nothing was written. Always
        leaves a `flight_trigger` event in the ring (cheap, so even an
        unarmed process shows the anomaly on its timeline) and counts
        dumps into flight_recorder_dumps_total{reason}."""
        # `or` would skip an EMPTY custom ring (SpanRecorder.__len__)
        rec = self.recorder if self.recorder is not None \
            else get_tracer()
        rec.event("flight_trigger", request=request, reason=str(reason),
                  **context)
        now = time.perf_counter()
        with self._lock:
            if self._dir is None:
                return None
            last = self._last.get(reason)
            if last is not None and now - last < self.min_interval_s:
                return None
            self._last[reason] = now
            self._seq += 1
            seq = self._seq
            out_dir = self._dir
        path = os.path.join(
            out_dir, f"flightrec_{reason}_{int(time.time() * 1000)}_"
                     f"{seq}.json")
        try:
            self._write(path, reason, rec, request, context,
                        since_us=(now - self.window_s) * 1e6)
        except OSError as e:
            # A diagnostics dump must never take down the serving step or
            # the watchdog thread (full disk / unwritable dir). Leave the
            # failure on the timeline, give the cooldown back so the next
            # anomaly retries, and count it.
            rec.event("flight_dump_failed", request=request,
                      reason=str(reason), error=str(e))
            with self._lock:
                if self._last.get(reason) == now:
                    del self._last[reason]
            get_registry().counter(
                "flight_recorder_dump_failures_total",
                help="anomaly dumps that failed to write",
                labels=("reason",)).labels(reason=str(reason)).inc()
            return None
        with self._lock:
            self.dumps.append(path)
        self._retain(path, reason, rec)
        get_registry().counter(
            "flight_recorder_dumps_total",
            help="anomaly dumps written by the flight recorder",
            labels=("reason",)).labels(reason=str(reason)).inc()
        return path

    def _write(self, path, reason, rec, request, context, since_us=None):
        spans = rec.spans(since_us=since_us)
        requests = []
        for s in spans:
            if s["request"] is not None and s["request"] not in requests:
                requests.append(s["request"])
        payload = {
            "schema": DUMP_SCHEMA,
            "time": time.time(),
            "reason": str(reason),
            "request": request,
            "context": {k: _clean_value(v, f"dump context {k!r}")
                        for k, v in context.items()},
            "window_s": self.window_s,
            "requests": requests,
            "spans": spans,
            "metrics": get_registry().snapshot(),
        }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
        return path

    def dump_to(self, path, reason="manual", request=None, **context):
        """Unconditional dump to an explicit path (no arming, no
        cooldown): the whole ring, not just the window — what
        serve_llama --trace and the bench trace leg write."""
        # `or` would skip an EMPTY custom ring (SpanRecorder.__len__)
        rec = self.recorder if self.recorder is not None \
            else get_tracer()
        out = self._write(path, reason, rec, request, context)
        with self._lock:
            self.dumps.append(out)
        # a manual dump landing INSIDE the armed dir participates in
        # retention like any trigger; explicit paths elsewhere are the
        # caller's to manage
        self._retain(out, reason, rec)
        return out


_flight = FlightRecorder()


def get_flight_recorder():
    """The process-wide flight recorder the serving/distributed anomaly
    triggers fire into."""
    return _flight


def write_dump(path, reason="manual", request=None, **context):
    """Dump the process-wide span ring + metrics snapshot to `path`."""
    return _flight.dump_to(path, reason=reason, request=request, **context)


def arm_default(out_dir=None, window_s=None,
                max_dumps=DEFAULT_MAX_DUMPS, max_bytes=DEFAULT_MAX_BYTES):
    """Server-entrypoint arming policy: the process flight recorder,
    bounded retention on. Long-running serve loops (serve_llama
    --continuous, serve_bench, serve_monitor) call this by default so a
    production p99 incident ships with its own evidence — the ROADMAP's
    "arm-by-default + dump retention" item. Dir resolution:
    `out_dir` arg > $PADDLE_TPU_FLIGHT_DIR > <tmp>/paddle_tpu_flightrec.
    Returns the armed recorder (disarm() to opt back out)."""
    if out_dir is None:
        out_dir = os.environ.get("PADDLE_TPU_FLIGHT_DIR") or os.path.join(
            tempfile.gettempdir(), "paddle_tpu_flightrec")
    return _flight.arm(out_dir, window_s=window_s, max_dumps=max_dumps,
                       max_bytes=max_bytes)


def operator_abort_dump(signal="KeyboardInterrupt", **context):
    """Final evidence write for an operator-initiated shutdown: serve
    entrypoints call this from their KeyboardInterrupt/SystemExit
    handlers so a Ctrl-C mid-incident still leaves a flight dump (the
    whole span window + a full metrics snapshot) instead of a dead
    process and no trail. When the process recorder is armed the dump
    goes through the normal trigger path (retention + manifest);
    unarmed processes get a best-effort dump in the default flight dir
    — unless NOTHING has run yet (recorder unarmed and the span ring
    empty: an argparse --help / bad-flag SystemExit has no evidence to
    preserve and must not litter dump files). Never raises: shutdown
    evidence must not turn an abort into a crash. Returns the dump
    path or None."""
    try:
        if _flight.armed:
            return _flight.trigger("operator_abort", signal=str(signal),
                                   **context)
        if len(get_tracer()) == 0:
            return None
        out_dir = os.environ.get("PADDLE_TPU_FLIGHT_DIR") or os.path.join(
            tempfile.gettempdir(), "paddle_tpu_flightrec")
        path = os.path.join(
            out_dir, f"flightrec_operator_abort_"
                     f"{int(time.time() * 1000)}_0.json")
        return _flight.dump_to(path, reason="operator_abort",
                               signal=str(signal), **context)
    except Exception:
        return None


def run_with_abort_evidence(fn):
    """Entrypoint wrapper shared by serve_llama / serve_bench /
    serve_monitor: run `fn()` and translate an operator abort
    (KeyboardInterrupt, or a SystemExit raised MID-RUN) into an
    `operator_abort` flight dump + the conventional exit code (130 for
    Ctrl-C). Returns the process exit code; one implementation so the
    three entrypoints cannot drift."""
    import sys

    try:
        rc = fn()
        return 0 if rc is None else rc
    except (KeyboardInterrupt, SystemExit) as e:
        path = operator_abort_dump(signal=type(e).__name__)
        if path:
            print(f"\noperator abort ({type(e).__name__}): flight dump "
                  f"+ metrics snapshot -> {path}", file=sys.stderr)
        if isinstance(e, KeyboardInterrupt):
            return 130
        # preserve SystemExit conventions: sys.exit() -> 0,
        # sys.exit(int) -> that code, sys.exit("msg") -> print + 1
        if e.code is None:
            return 0
        if isinstance(e.code, int):
            return e.code
        print(e.code, file=sys.stderr)
        return 1


def load_manifest(dump_dir):
    """Load + schema-validate a retention manifest
    (``<dir>/flightrec_manifest.json``; stdlib only, same contract as
    load_dump). Raises ValueError on anything that is not a v1
    manifest, OSError when the dir has none."""
    path = os.path.join(str(dump_dir), MANIFEST_NAME)
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(
            f"{path}: not a {MANIFEST_SCHEMA} manifest (schema="
            f"{data.get('schema') if isinstance(data, dict) else None!r})")
    if not isinstance(data.get("dumps"), list):
        raise ValueError(f"{path}: manifest dumps is not a list")
    for i, e in enumerate(data["dumps"]):
        if not {"file", "reason", "time", "bytes"} <= set(e):
            raise ValueError(f"{path}: manifest entry {i} malformed: "
                             f"{sorted(e)}")
    return data


def load_dump(path):
    """Load + schema-validate a flight-recorder dump (stdlib only — the
    same loader tools/request_trace.py and the --selfcheck use).
    Raises ValueError on anything that is not a v1 dump."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("schema") != DUMP_SCHEMA:
        raise ValueError(
            f"{path}: not a {DUMP_SCHEMA} dump "
            f"(schema={data.get('schema') if isinstance(data, dict) else None!r})")
    missing = {"time", "reason", "window_s", "requests", "spans",
               "metrics"} - set(data)
    if missing:
        raise ValueError(f"{path}: dump missing keys {sorted(missing)}")
    if not isinstance(data["spans"], list):
        raise ValueError(f"{path}: spans is not a list")
    for i, s in enumerate(data["spans"]):
        if not {"name", "ts_us", "dur_us", "request", "args"} <= set(s):
            raise ValueError(f"{path}: span {i} malformed: {sorted(s)}")
    return data
