"""Registry exporters: Prometheus text, JSON snapshot, chrome counters.

Three consumers, three formats:

* ``to_prometheus`` — the scrape endpoint / pushgateway format
  (text exposition 0.0.4): ``# HELP`` / ``# TYPE`` headers, labeled
  samples, histogram ``_bucket{le=...}`` / ``_sum`` / ``_count`` series
  with cumulative bucket counts.
* ``to_json`` — one self-describing dict for dashboards and for
  committing bench snapshots (BASELINE.md); stable key order.
* ``chrome_counter_events`` — the registry's timeline ring as
  ``"ph": "C"`` counter events. Profiler._export_chrome merges these
  into the host-range stream so serving gauges and op ranges land on ONE
  chrome://tracing timeline.

stdlib only, same reason as metrics.py.
"""
import json
import math
import re
import time

from .metrics import get_registry

__all__ = ["to_prometheus", "to_json", "chrome_counter_events",
           "parse_prometheus"]


def _esc_label(v):
    """Label-VALUE escaping (text exposition 0.0.4): backslash, double
    quote, newline — the value sits inside double quotes."""
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _esc_help(v):
    """HELP-text escaping is a DIFFERENT rule in the same format:
    only backslash and newline. Help text is not quoted, so escaping
    `"` (as the old shared `_esc` did) rendered help strings containing
    quotes as literal `\\"` in every scrape."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _labelstr(names, values, extra=()):
    pairs = [f'{n}="{_esc_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_esc_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(v):
    if v != v:                       # NaN
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def to_prometheus(registry=None):
    """Text exposition format; one string ready to serve at /metrics."""
    registry = registry or get_registry()
    lines = []
    for m in registry.metrics():
        lines.append(f"# HELP {m.name} {_esc_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        # copy child state under the lock: a concurrent observe() between
        # reading the buckets and the count would otherwise emit a scrape
        # where x_count disagrees with the +Inf bucket (which Prometheus
        # treats as the count — histogram_quantile turns that into NaN)
        with registry._lock:
            if m.kind == "histogram":
                children = {k: (list(c.bucket_counts), c.sum, c.count)
                            for k, c in m._children.items()}
            else:
                children = {k: c.value for k, c in m._children.items()}
        for key, child in sorted(children.items()):
            if m.kind == "histogram":
                bucket_counts, csum, ccount = child
                cum = 0
                for edge, n in zip(m.buckets, bucket_counts):
                    cum += n
                    lines.append(
                        f"{m.name}_bucket"
                        + _labelstr(m.labelnames, key,
                                    extra=[("le", _fmt(edge))])
                        + f" {cum}")
                cum += bucket_counts[-1]
                lines.append(
                    f"{m.name}_bucket"
                    + _labelstr(m.labelnames, key, extra=[("le", "+Inf")])
                    + f" {cum}")
                lines.append(f"{m.name}_sum"
                             + _labelstr(m.labelnames, key)
                             + f" {_fmt(csum)}")
                lines.append(f"{m.name}_count"
                             + _labelstr(m.labelnames, key)
                             + f" {ccount}")
            else:
                lines.append(f"{m.name}"
                             + _labelstr(m.labelnames, key)
                             + f" {_fmt(child)}")
    return "\n".join(lines) + ("\n" if lines else "")


def to_json(registry=None, indent=None):
    """JSON string: {"time": unix_seconds, "metrics": snapshot()}."""
    registry = registry or get_registry()
    return json.dumps({"time": time.time(),
                       "metrics": registry.snapshot()},
                      indent=indent, sort_keys=True)


_SAMPLE_RE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:\\.|[^"\\])*)"')


_UNESC_RE = re.compile(r"\\(.)")


def _unesc_label(v):
    # ONE left-to-right pass: sequential .replace() calls corrupt a
    # literal backslash-then-n ('\\' + 'n' escapes to '\\\\n', which a
    # naive '\\n'-first pass turns into backslash + real newline).
    # Unknown escapes keep their backslash, like Prometheus' parser.
    return _UNESC_RE.sub(
        lambda m: {"n": "\n", '"': '"', "\\": "\\"}.get(
            m.group(1), "\\" + m.group(1)), v)


def _parse_value(v):
    if v == "+Inf":
        return math.inf
    if v == "-Inf":
        return -math.inf
    if v == "NaN":
        return math.nan
    return float(v)


def parse_prometheus(text):
    """Parse text exposition 0.0.4 back into
    ``{family: {"kind": str|None, "help": str|None,
    "samples": [(name, {label: value}, float), ...]}}``.

    The inverse of :func:`to_prometheus`, close enough for a scraper:
    histogram series land under their family name (``x_bucket`` /
    ``x_sum`` / ``x_count`` grouped under ``x`` once a ``# TYPE x
    histogram`` header announced it; standalone they are their own
    family). This is what ``tools/serve_monitor.py --scrape`` renders a
    dashboard from and the gateway gate validates /metrics with —
    stdlib-only, same contract as the rest of the module."""
    out = {}
    histograms = set()

    def fam(name):
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) \
                    and base[:-len(suffix)] in histograms:
                base = base[:-len(suffix)]
                break
        return out.setdefault(base, {"kind": None, "help": None,
                                     "samples": []})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            out.setdefault(name, {"kind": None, "help": None,
                                  "samples": []})["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            out.setdefault(name, {"kind": None, "help": None,
                                  "samples": []})["kind"] = kind.strip()
            if kind.strip() == "histogram":
                histograms.add(name)
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, _, labelstr, value = m.groups()
        labels = {k: _unesc_label(v)
                  for k, v in _LABEL_RE.findall(labelstr or "")}
        fam(name)["samples"].append((name, labels, _parse_value(value)))
    return out


def chrome_counter_events(registry=None, pid=None, since_us=None,
                          until_us=None):
    """Timeline samples as chrome-trace counter events.

    One ``{"ph": "C"}`` event per recorded sample, so gauges plot as a
    stepped series alongside the profiler's "X" host ranges. ``dur`` and
    ``tid`` carry 0: counters have no duration, and keeping the keys
    means every event in the merged stream has the same shape (the
    profiler's export contract). ``since_us``/``until_us`` (perf_counter
    microseconds, the samples' timebase) window the ring — the profiler
    passes its record window so a short trace doesn't drag in every
    sample since process start."""
    registry = registry or get_registry()
    if pid is None:
        import os
        pid = os.getpid()
    return [{"name": name, "ph": "C", "ts": ts, "dur": 0,
             "pid": pid, "tid": 0, "cat": "metric",
             "args": {"value": value}}
            for ts, name, value in registry.timeline()
            if (since_us is None or ts >= since_us)
            and (until_us is None or ts <= until_us)]
