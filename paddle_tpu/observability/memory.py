"""Live-array census + device-memory accounting + HBM-pressure trigger.

The memory half of the cost/memory observability layer (costs.py is the
compute half): WHERE the bytes are, not just how many the programs
touch.

* ``live_array_census()`` — every live ``jax.Array`` in the process
  grouped by ``dtype[shape]`` (or an owner tag registered via
  ``tag_arrays()``): {group: {count, bytes}}. The serving engine's leak
  contract rides on this — after submit/retire churn the census must
  return to its pre-admission state (tests/test_cost_memory.py pins
  it), because a leaked KV slab is invisible to the allocator's own
  block accounting.
* ``record_census()`` — census into ``live_arrays{group}`` /
  ``live_array_bytes{group}`` gauges plus process totals with
  high-water tracking.
* ``MemoryMonitor`` — per-device in-use/limit gauges (PJRT
  ``memory_stats()`` where the backend has it, census bytes as the
  fallback) and the ``hbm_pressure`` flight-recorder trigger: when
  headroom drops below ``min_headroom_frac`` of the budget, the span
  window + metrics snapshot dump fires — the OOM's black box, written
  BEFORE the allocator starts failing. ``tick()`` is cadence-gated so
  a serving engine can call it every step (the SLOMonitor pattern).
* ``shard_skew()`` — per-device byte placement of a sharded pytree and
  the max/mean skew ratio, the load-balance gauge for the virtual
  8-device mesh legs (a skewed TP/FSDP layout shows up here before it
  shows up as a straggler collective).

Same constraints as every observability module: stdlib-only at import
(jax is touched lazily and its absence degrades to empty censuses, so
the bare-container selfcheck can exercise the monitor with injected
numbers), host-side only, lock-free reads of jax's own bookkeeping.
"""
import threading
import time
import weakref

from .metrics import get_registry
from .tracing import get_flight_recorder

__all__ = [
    "live_array_census", "census_diff", "record_census", "tag_arrays",
    "device_memory", "MemoryMonitor", "shard_skew",
]

# id(array) -> (weakref, owner tag): tags survive exactly as long as the
# array; a dead weakref drops out of the census grouping automatically
_tags = {}
_tags_lock = threading.Lock()


def tag_arrays(owner, arrays):
    """Attribute arrays to an owner for census grouping (jax arrays take
    weakrefs; the tag dies with the array)."""
    with _tags_lock:
        for a in arrays:
            try:
                ref = weakref.ref(a)
            except TypeError:
                continue
            _tags[id(a)] = (ref, str(owner))


def _tag_of(arr):
    with _tags_lock:
        ent = _tags.get(id(arr))
        if ent is None:
            return None
        ref, owner = ent
        live = ref()
        if live is None or live is not arr:
            del _tags[id(arr)]      # id reused by a different object
            return None
        return owner


def _gc_tags():
    with _tags_lock:
        dead = [k for k, (ref, _) in _tags.items() if ref() is None]
        for k in dead:
            del _tags[k]


def live_array_census(collect=True):
    """{group: {"count": n, "bytes": b}} over ``jax.live_arrays()``;
    group is the owner tag when registered, else ``dtype[shape]``.
    Returns {} without jax (bare container). ``collect=True`` runs a
    gc pass first so droppable references don't read as leaks."""
    try:
        import jax
    except Exception:
        return {}
    if collect:
        import gc
        gc.collect()
    _gc_tags()
    out = {}
    for a in jax.live_arrays():
        try:
            key = _tag_of(a) or f"{a.dtype}{list(a.shape)}"
            nbytes = int(a.nbytes)
        except Exception:
            continue
        ent = out.setdefault(key, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += nbytes
    return out


def census_diff(before, after):
    """{group: {"count": delta, "bytes": delta}} for groups that
    changed — empty dict == no leak (the step-boundary contract)."""
    out = {}
    for key in set(before) | set(after):
        b = before.get(key, {"count": 0, "bytes": 0})
        a = after.get(key, {"count": 0, "bytes": 0})
        dc, db = a["count"] - b["count"], a["bytes"] - b["bytes"]
        if dc or db:
            out[key] = {"count": dc, "bytes": db}
    return out


def record_census(census=None, registry=None):
    """Land a census in the registry: per-group count/bytes gauges plus
    process totals with a high-water mark. ``census=None`` takes a live
    one (pass a dict to replay a synthetic census — the selfcheck
    path). Returns the census."""
    if census is None:
        census = live_array_census()
    reg = registry if registry is not None else get_registry()
    counts = reg.gauge("live_arrays",
                       help="live jax arrays by census group",
                       labels=("group",))
    sizes = reg.gauge("live_array_bytes",
                      help="bytes held by live jax arrays, by group",
                      labels=("group",))
    total_c = total_b = 0
    # census groups are dtype[shape]/owner-tag strings: bounded by the
    # program's own array-shape set (and stale groups are zeroed below,
    # so even that set can't ratchet) — not per-request identity
    for key, ent in census.items():
        counts.labels(group=key).set(ent["count"])      # graftlint: disable=GL112
        sizes.labels(group=key).set(ent["bytes"])       # graftlint: disable=GL112
        total_c += ent["count"]
        total_b += ent["bytes"]
    # groups that vanished since the last census must read 0, not keep
    # exporting their last value forever (a freed 4 GB KV cache would
    # otherwise look resident on every later scrape)
    for fam in (counts, sizes):
        for key in list(fam._children):
            if key and key[0] not in census:
                fam.labels(group=key[0]).set(0)
    reg.gauge("live_arrays_total",
              help="live jax arrays in the process").set(total_c)
    reg.gauge("live_array_bytes_total",
              help="bytes held by all live jax arrays").set(total_b)
    reg.gauge("live_array_bytes_high_water",
              help="peak bytes ever held by live arrays "
                   "(census-time high-water)").set_max(total_b)
    return census


def device_memory():
    """Per-device memory stats from PJRT: {device: {"bytes_in_use":,
    "bytes_limit":, "peak_bytes_in_use":}} — only devices whose backend
    reports stats (CPU reports none; the census is the fallback)."""
    try:
        import jax
    except Exception:
        return {}
    out = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out[str(d)] = {
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        }
    return out


def shard_skew(tree, registry=None):
    """Per-device byte placement of a (possibly sharded) array pytree:
    sets ``shard_bytes{device}`` gauges and the ``shard_skew`` ratio
    (max device bytes / mean device bytes; 1.0 == perfectly balanced).
    Returns {"devices": {...}, "skew": r} — {} without jax or on an
    empty tree."""
    try:
        import jax
    except Exception:
        return {}
    per_device = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            continue
        for s in shards:
            try:
                per_device[str(s.device)] = per_device.get(
                    str(s.device), 0) + int(s.data.nbytes)
            except Exception:
                continue
    if not per_device:
        return {}
    reg = registry if registry is not None else get_registry()
    g = reg.gauge("shard_bytes",
                  help="bytes of the last skew-checked pytree resident "
                       "per device", labels=("device",))
    # device ids are the fixed hardware topology, not traffic-scoped
    for dev, b in per_device.items():
        g.labels(device=dev).set(b)     # graftlint: disable=GL112
    # devices absent from THIS pytree read 0, not their previous value
    # (the record_census stale-group contract): the per-device view
    # must agree with the skew ratio computed right here
    for key in list(g._children):
        if key and key[0] not in per_device:
            g.labels(device=key[0]).set(0)
    mean = sum(per_device.values()) / len(per_device)
    skew = max(per_device.values()) / mean if mean > 0 else 0.0
    reg.gauge("shard_skew",
              help="max/mean per-device bytes of the last skew-checked "
                   "pytree (1.0 = balanced)").set(skew)
    return {"devices": per_device, "skew": skew}


class MemoryMonitor:
    """Cadence-gated HBM accounting + pressure trigger (the SLOMonitor
    shape: construct once, ``tick()`` from the serve/train loop).

    ``budget_bytes`` is the accounting ceiling: the device's
    ``bytes_limit`` when PJRT reports one, else whatever the caller
    declares (a CPU test budget, a fraction of host RAM, ...). When
    in-use bytes leave less than ``min_headroom_frac`` of the budget
    free, the flight recorder fires ``hbm_pressure`` — once per
    recorder cooldown, with the in-use/budget/headroom context in the
    dump. No budget -> gauges only, never a trigger.

    ``interval_s`` defaults to 1s (the SLOMonitor cadence): on
    backends without PJRT memory stats an accounting pass is a full
    census — gc pass included — and running THAT per decode step would
    inflate the very latencies the SLO engine next to it measures.
    ``interval_s=0`` opts into per-tick accounting (tests)."""

    def __init__(self, budget_bytes=None, min_headroom_frac=0.1,
                 interval_s=1.0, registry=None, flight_recorder=None):
        self.budget_bytes = None if budget_bytes is None \
            else float(budget_bytes)
        self.min_headroom_frac = float(min_headroom_frac)
        if not 0.0 <= self.min_headroom_frac < 1.0:
            raise ValueError("min_headroom_frac must be in [0, 1)")
        self.interval_s = float(interval_s)
        self._registry = registry
        self._flight = flight_recorder
        self._last_tick = None
        self.pressure_events = 0
        self.last_report = None

    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    def tick(self, now=None):
        """Cadence gate around update(): cheap monotonic compare when
        the interval has not elapsed (the per-step serving hook)."""
        now = time.monotonic() if now is None else now
        if self._last_tick is not None \
                and now - self._last_tick < self.interval_s:
            return None
        self._last_tick = now
        return self.update()

    def update(self, in_use_bytes=None, budget_bytes=None):
        """One accounting pass: census + device stats -> gauges, then
        the pressure check. ``in_use_bytes`` overrides the measured
        value (synthetic numbers — the selfcheck path)."""
        reg = self._reg()
        budget = budget_bytes if budget_bytes is not None \
            else self.budget_bytes
        devs = device_memory() if in_use_bytes is None else {}
        census_bytes = None
        if in_use_bytes is None:
            if devs:
                in_use = reg.gauge(
                    "hbm_device_bytes_in_use",
                    help="per-device memory in use (PJRT stats)",
                    labels=("device",))
                limit_g = reg.gauge(
                    "hbm_device_bytes_limit",
                    help="per-device memory capacity (PJRT stats)",
                    labels=("device",))
                peak_g = reg.gauge(
                    "hbm_device_bytes_peak",
                    help="per-device peak memory in use (PJRT stats)",
                    labels=("device",))
                # device ids: fixed hardware set, bounded by topology
                for dev, st in devs.items():
                    in_use.labels(device=dev).set(      # graftlint: disable=GL112
                        st["bytes_in_use"])
                    if st["bytes_limit"]:
                        limit_g.labels(device=dev).set(  # graftlint: disable=GL112
                            st["bytes_limit"])
                    if st["peak_bytes_in_use"]:
                        peak_g.labels(device=dev).set(   # graftlint: disable=GL112
                            st["peak_bytes_in_use"])
                in_use_bytes = sum(d["bytes_in_use"] for d in devs.values())
                limits = sum(d["bytes_limit"] for d in devs.values())
                if budget is None and limits:
                    budget = float(limits)
            else:
                census = record_census(registry=reg)
                census_bytes = sum(e["bytes"] for e in census.values())
                in_use_bytes = census_bytes
        # pressure is PER DEVICE where the backend reports limits: an
        # unbalanced placement (the condition shard_skew exists to
        # catch) can OOM device 0 while the fleet AGGREGATE still reads
        # 20% full — the trigger below uses the worst device's headroom
        worst_dev = None
        for dev, st in devs.items():
            if st["bytes_limit"]:
                h = max(0.0, (st["bytes_limit"] - st["bytes_in_use"])
                        / st["bytes_limit"])
                if worst_dev is None or h < worst_dev[1]:
                    worst_dev = (dev, h)
        in_use_bytes = float(in_use_bytes)
        g = reg.gauge("hbm_bytes_in_use",
                      help="device memory in use (PJRT stats, or live-"
                           "array census bytes where the backend "
                           "reports none)")
        g.set(in_use_bytes)
        reg.gauge("hbm_bytes_high_water",
                  help="peak observed hbm_bytes_in_use").set_max(
                      in_use_bytes)
        headroom = None
        if budget:
            headroom = max(0.0, (budget - in_use_bytes) / budget)
            reg.gauge("hbm_bytes_budget",
                      help="accounting ceiling for the pressure check "
                           "(device bytes_limit, or a declared "
                           "budget)").set(budget)
            reg.gauge("hbm_headroom_frac",
                      help="(budget - in_use) / budget; the hbm_pressure"
                           " trigger fires below min_headroom_frac").set(
                          headroom)
        # the trigger evaluates the TIGHTEST headroom it can see: the
        # worst single device when per-device limits exist, the declared
        # budget otherwise
        eff_headroom = headroom
        if worst_dev is not None and (eff_headroom is None
                                      or worst_dev[1] < eff_headroom):
            eff_headroom = worst_dev[1]
        pressure = eff_headroom is not None \
            and eff_headroom < self.min_headroom_frac
        report = {"in_use_bytes": in_use_bytes,
                  "budget_bytes": budget,
                  "headroom_frac": headroom,
                  "worst_device": None if worst_dev is None
                  else {"device": worst_dev[0],
                        "headroom_frac": worst_dev[1]},
                  "census_bytes": census_bytes,
                  "devices": devs,
                  "pressure": pressure}
        self.last_report = report
        if pressure:
            self.pressure_events += 1
            fr = self._flight if self._flight is not None \
                else get_flight_recorder()
            ctx = {"in_use_bytes": in_use_bytes,
                   "budget_bytes": budget,
                   "headroom_frac": eff_headroom,
                   "min_headroom_frac": self.min_headroom_frac}
            if worst_dev is not None:
                ctx["worst_device"] = worst_dev[0]
            fr.trigger("hbm_pressure", **ctx)
        return report
