"""Declarative serving SLOs + multi-window burn-rate evaluation.

The monitoring loop a production serving fleet runs NEXT TO the
scheduler: objectives are declared once (``p99 ttft < 0.5s``,
``kv_alloc_failure ratio < 0.1%``), and every evaluation asks the
time-series layer (timeseries.py) how fast each objective's error
budget is burning, SRE-style, over TWO windows at once:

* a **fast** window with a high burn threshold catches cliffs — a
  sudden regression torches the budget at 10x+ and should page within
  seconds;
* a **slow** window with a low threshold catches slow burns — a 2x
  burn never trips the fast alarm but exhausts a month's budget in two
  weeks.

Burn rate is the classic ratio: ``bad_fraction / error_budget``. For a
quantile objective (``p99 ttft < X``) the budget is ``1 - q`` (1% of
requests may exceed X) and the bad fraction is the share of the
window's observations above X (delta-histogram interpolation). For a
ratio objective (``kv_alloc_failure ratio < Z``) the budget is Z
itself and the bad fraction is ``delta(num) / delta(den)`` — a zero
budget means ANY bad event is an infinite burn.

A breach (burn >= the window's threshold) lands three ways at once so
an incident ships with its own evidence:

* ``slo_breaches_total{objective,window}`` counters in the registry,
* an ``slo_breach`` event on the engine's timeline lane,
* a flight-recorder ``slo_burn_rate`` trigger — the dump carries the
  last window of request spans + the full metrics snapshot (per-reason
  cooldown keeps a sustained breach from flooding the dump dir; the
  retention policy bounds it regardless).

``SLOMonitor`` packages a TimeSeries + SLOEngine behind the host-side
cadence hook the serving loop calls every step (``tick()`` — cheap
no-op until ``cadence_s`` elapsed). stdlib-only at import, same
contract as the rest of the package.
"""
import math
import time

# NOTE: from-imports, not `from . import tracing` — the bare-submodule
# form breaks the standalone by-path load (tools/metrics_snapshot.py in
# a bare container; see the package __init__ for the full story)
from .metrics import get_registry
from .timeseries import TimeSeries
from .tracing import get_flight_recorder, get_tracer

__all__ = ["Objective", "SLOEngine", "SLOMonitor", "DEFAULT_WINDOWS",
           "REPORT_SCHEMA", "validate_report", "json_safe"]

REPORT_SCHEMA = "paddle_tpu.slo_report/1"

# SRE multi-window defaults: the fast window catches cliffs (a 14x burn
# exhausts ~1.7% of a 30-day budget per hour), the slow window catches
# slow burns a cliff detector never sees. Serving configs override both
# (the CI leg shrinks them to seconds).
DEFAULT_WINDOWS = (
    {"name": "fast", "window_s": 30.0, "burn_threshold": 14.0},
    {"name": "slow", "window_s": 300.0, "burn_threshold": 2.0},
)


class Objective:
    """One declarative SLO, JSON-friendly both ways.

    kind="quantile": `q` of `metric` (a histogram) must stay < `max` —
      budget = 1 - q, bad fraction = share of windowed observations
      above `max`.
    kind="ratio": delta(`num`) / delta(`den`) (two counters) must stay
      < `max` — budget = `max`, zero budget = any bad event breaches.
    `min_count` guards noise: a window with fewer observations (or a
    smaller denominator delta) than this does not evaluate at all —
    two slow requests at startup are not a p99 regression.
    """

    KINDS = ("quantile", "ratio")

    def __init__(self, name, kind, max, metric=None, q=None,
                 num=None, den=None, min_count=1):
        self.name = str(name)
        if kind not in self.KINDS:
            raise ValueError(f"objective {name}: unknown kind {kind!r} "
                             f"(have {self.KINDS})")
        self.kind = kind
        self.max = float(max)
        self.min_count = int(min_count)
        if kind == "quantile":
            if not metric or q is None or not 0 < float(q) < 1:
                raise ValueError(
                    f"objective {name}: quantile needs metric= and "
                    f"0 < q < 1 (got metric={metric!r} q={q!r})")
            if self.max <= 0:
                raise ValueError(f"objective {name}: max must be > 0")
            self.metric, self.q = str(metric), float(q)
            self.num = self.den = None
        else:
            if not num or not den:
                raise ValueError(
                    f"objective {name}: ratio needs num= and den=")
            if self.max < 0:
                raise ValueError(f"objective {name}: max must be >= 0")
            self.num, self.den = str(num), str(den)
            self.metric = self.q = None

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        return cls(d.pop("name"), d.pop("kind"), d.pop("max"), **d)

    def to_dict(self):
        out = {"name": self.name, "kind": self.kind, "max": self.max,
               "min_count": self.min_count}
        if self.kind == "quantile":
            out["metric"], out["q"] = self.metric, self.q
        else:
            out["num"], out["den"] = self.num, self.den
        return out

    def describe(self):
        if self.kind == "quantile":
            return (f"p{self.q * 100:g} {self.metric} < {self.max:g}")
        return f"{self.num}/{self.den} < {self.max:g}"

    # -- evaluation over one window --------------------------------------
    def evaluate(self, ts, window_s, now=None):
        """{'value','bad_fraction','burn_rate','count'} over the window
        ending at `now`, or None when the window holds too little data
        to judge (below min_count — absence of traffic is not health
        AND not a breach)."""
        if self.kind == "quantile":
            n = ts.count(self.metric, window_s, now=now)
            if n is None or n < self.min_count:
                return None
            bad = ts.fraction_over(self.metric, self.max, window_s,
                                   now=now) or 0.0
            budget = 1.0 - self.q
            burn = bad / budget if budget > 0 else (
                math.inf if bad > 0 else 0.0)
            return {"value": ts.quantile(self.metric, self.q, window_s,
                                         now=now),
                    "bad_fraction": bad, "burn_rate": burn, "count": n}
        dn = ts.delta(self.num, window_s, now=now)
        dd = ts.delta(self.den, window_s, now=now)
        if dn is None or dd is None or dd < self.min_count:
            return None
        bad = dn / dd
        burn = bad / self.max if self.max > 0 else (
            math.inf if bad > 0 else 0.0)
        return {"value": bad, "bad_fraction": bad, "burn_rate": burn,
                "count": dd}


class SLOEngine:
    """Evaluate objectives x windows against a TimeSeries; record
    breaches into the registry / timeline / flight recorder."""

    def __init__(self, objectives, windows=DEFAULT_WINDOWS,
                 timeseries=None, registry=None, recorder=None,
                 flight_recorder=None):
        self.objectives = [o if isinstance(o, Objective)
                           else Objective.from_dict(o) for o in objectives]
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.windows = []
        for w in windows:
            w = dict(w)
            if float(w["window_s"]) <= 0 or float(w["burn_threshold"]) <= 0:
                raise ValueError(f"window {w}: window_s and "
                                 "burn_threshold must be > 0")
            self.windows.append({"name": str(w["name"]),
                                 "window_s": float(w["window_s"]),
                                 "burn_threshold":
                                     float(w["burn_threshold"])})
        self.timeseries = timeseries if timeseries is not None \
            else TimeSeries(registry=registry)
        self.registry = registry        # None = process registry
        self.recorder = recorder        # None = process tracer
        self.flight_recorder = flight_recorder  # None = process recorder
        self.evaluations = 0
        self.breaches_total = 0
        self.breach_counts = {}         # (objective, window) -> count
        self.last_report = None

    def _breach_counter(self):
        reg = self.registry if self.registry is not None else get_registry()
        return reg.counter(
            "slo_breaches_total",
            help="SLO burn-rate breaches (objective x evaluation window)",
            labels=("objective", "window"))

    def evaluate(self, now=None):
        """One pass over objectives x windows; returns (and stores) the
        report dict. Breaches increment slo_breaches_total, leave an
        slo_breach timeline event, and fire the flight recorder with
        reason `slo_burn_rate` (its per-reason cooldown rate-limits a
        sustained breach)."""
        now = time.monotonic() if now is None else float(now)
        rec = self.recorder if self.recorder is not None \
            else get_tracer()
        flight = self.flight_recorder if self.flight_recorder is not None \
            else get_flight_recorder()
        self.evaluations += 1
        report = {"schema": REPORT_SCHEMA, "now": now,
                  "windows": [dict(w) for w in self.windows],
                  "objectives": [], "breaches": 0,
                  "breaches_total": self.breaches_total}
        for obj in self.objectives:
            entry = {"name": obj.name, "kind": obj.kind,
                     "max": obj.max, "describe": obj.describe(),
                     "windows": {}, "breached": False}
            for w in self.windows:
                ev = obj.evaluate(self.timeseries, w["window_s"], now=now)
                if ev is None:
                    entry["windows"][w["name"]] = None
                    continue
                burn = ev["burn_rate"]
                breached = burn >= w["burn_threshold"]
                ev = dict(ev, burn_threshold=w["burn_threshold"],
                          breached=breached,
                          burn_rate=burn if math.isfinite(burn)
                          else float("inf"))
                entry["windows"][w["name"]] = ev
                if not breached:
                    continue
                entry["breached"] = True
                report["breaches"] += 1
                self.breaches_total += 1
                key = (obj.name, w["name"])
                self.breach_counts[key] = self.breach_counts.get(key, 0) + 1
                self._breach_counter().labels(
                    objective=obj.name, window=w["name"]).inc()
                burn_arg = burn if math.isfinite(burn) else -1.0
                rec.event("slo_breach", objective=obj.name,
                          window=w["name"], burn_rate=burn_arg,
                          value=ev["value"],
                          bad_fraction=ev["bad_fraction"])
                flight.trigger(
                    "slo_burn_rate", objective=obj.name,
                    window=w["name"], window_s=w["window_s"],
                    burn_rate=burn_arg, threshold=obj.max,
                    value=ev["value"], count=ev["count"])
            report["objectives"].append(entry)
        report["breaches_total"] = self.breaches_total
        self.last_report = report
        return report


def json_safe(obj):
    """Deep copy with non-finite floats spelled as strings ("+Inf",
    "-Inf", "NaN" — the Prometheus exposition spelling). A zero-budget
    ratio breach carries burn_rate = math.inf, which json.dump would
    emit as a bare ``Infinity`` literal — valid to Python's loads, but
    not RFC 8259 JSON, so jq/JS/Go consumers of a serve_monitor report
    would reject the whole file. The in-memory report keeps the real
    float (dashboards compare against thresholds); this runs at the
    serialization boundary only."""
    if isinstance(obj, float) and not math.isfinite(obj):
        if math.isnan(obj):
            return "NaN"
        return "+Inf" if obj > 0 else "-Inf"
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def validate_report(report):
    """Schema-check an SLO report (the serve_monitor JSON embeds one;
    stdlib-only, same contract as tracing.load_dump). Raises ValueError
    on anything that is not a v1 report; returns the report."""
    if not isinstance(report, dict) or report.get("schema") != REPORT_SCHEMA:
        raise ValueError(
            f"not a {REPORT_SCHEMA} report (schema="
            f"{report.get('schema') if isinstance(report, dict) else None!r})")
    missing = {"now", "windows", "objectives", "breaches",
               "breaches_total"} - set(report)
    if missing:
        raise ValueError(f"SLO report missing keys {sorted(missing)}")
    if not isinstance(report["objectives"], list):
        raise ValueError("SLO report objectives is not a list")
    for i, o in enumerate(report["objectives"]):
        if not {"name", "kind", "max", "windows", "breached"} <= set(o):
            raise ValueError(f"SLO report objective {i} malformed: "
                             f"{sorted(o)}")
        for wname, ev in o["windows"].items():
            if ev is None:
                continue
            if not {"burn_rate", "bad_fraction", "count",
                    "breached"} <= set(ev):
                raise ValueError(
                    f"SLO report objective {o['name']} window {wname} "
                    f"malformed: {sorted(ev)}")
    return report


class SLOMonitor:
    """TimeSeries + SLOEngine behind the serve loop's cadence hook.

    The engine calls ``tick()`` once per step (host-side, after the
    compiled step completed); until ``cadence_s`` has elapsed since the
    last evaluation that is one monotonic read and a compare. On
    cadence: one registry sample into the rings, one burn-rate pass.
    Construction is declarative (``SLOMonitor.from_config(json_dict)``)
    so tools/serve_slo.json can carry the whole policy."""

    def __init__(self, objectives, windows=DEFAULT_WINDOWS,
                 cadence_s=1.0, capacity=1024, registry=None,
                 recorder=None, flight_recorder=None):
        if float(cadence_s) < 0:
            raise ValueError("cadence_s must be >= 0")
        self.cadence_s = float(cadence_s)
        self.timeseries = TimeSeries(registry=registry, capacity=capacity)
        self.engine = SLOEngine(objectives, windows=windows,
                                timeseries=self.timeseries,
                                registry=registry, recorder=recorder,
                                flight_recorder=flight_recorder)
        self._last = None
        self.ticks = 0

    @classmethod
    def from_config(cls, config, **overrides):
        """Build from a JSON-friendly dict: {"objectives": [...],
        "windows": [...], "cadence_s": ..., "capacity": ...} — the
        `monitor` block of tools/serve_slo.json."""
        kw = {"objectives": config["objectives"]}
        for k in ("windows", "cadence_s", "capacity"):
            if k in config:
                kw[k] = config[k]
        kw.update(overrides)
        return cls(**kw)

    @property
    def last_report(self):
        return self.engine.last_report

    @property
    def breaches_total(self):
        return self.engine.breaches_total

    def tick(self, now=None):
        """The per-step hook: no-op until the cadence elapses, then
        sample + evaluate. Returns the report when an evaluation ran,
        None otherwise."""
        now = time.monotonic() if now is None else float(now)
        if self._last is not None and now - self._last < self.cadence_s:
            return None
        self._last = now
        self.timeseries.sample(now)
        return self.engine.evaluate(now)

    def force(self, now=None):
        """Sample + evaluate regardless of cadence (end-of-run report)."""
        now = time.monotonic() if now is None else float(now)
        self._last = now
        self.timeseries.sample(now)
        return self.engine.evaluate(now)

    def report(self, now=None):
        """The /slo endpoint's view: the last cadence evaluation when
        one exists, else a fresh forced one — a gateway scraped before
        the first cadence tick still answers with a schema-valid
        report instead of null. Call on the thread that owns tick()
        (the gateway routes it through the stepper)."""
        rep = self.engine.last_report
        return rep if rep is not None else self.force(now)
