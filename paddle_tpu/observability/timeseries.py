"""Windowed time series over the metrics registry.

PR 3's registry answers "what is the cumulative state"; this module
answers "what happened in the LAST N SECONDS". A long-running server's
SLO is windowed by definition — "p99 TTFT over the last minute", not
"p99 since process start" (a process that was slow for its first hour
and fast ever since still reports an awful lifetime p99) — so the SLO
engine (slo.py) needs deltas between registry snapshots, not the
snapshots themselves.

One ``TimeSeries`` holds a bounded ring per metric child: ``sample()``
walks the registry under its lock and appends ``(ts, payload)`` — a
float for counters/gauges, ``(bucket_counts, sum, count)`` for
histograms — and the query side subtracts the sample at the window's
left edge from the newest one:

* ``rate(name, window_s)`` — counter increase per second over the window
  (None across a registry reset — a negative delta is a reset, not a
  rate).
* ``quantile(name, q, window_s)`` — delta-histogram quantile: the
  observations RECORDED INSIDE the window, interpolated exactly like
  ``Histogram.quantile`` (p99 TTFT over the last N seconds).
* ``fraction_over(name, threshold, window_s)`` — what share of the
  window's observations exceeded ``threshold`` (the burn-rate
  numerator: bad events / events).
* ``gauge_stats(name, window_s)`` — min/max/mean/last of the sampled
  gauge values in the window.

Same design constraints as the rest of the package: stdlib-only at
import (the tier-0 selfcheck loads this in a bare container),
lock-protected (the serve loop samples while an exporter reads), and
host-side only — every value came through the registry's ``float()``
tracer guard already. Timestamps default to ``time.monotonic()`` (the
latency-bookkeeping clock, immune to wall-clock jumps); every entry
point takes an explicit ``now=`` so tests and the selfcheck can replay
synthetic streams deterministically.
"""
import collections
import threading
import time

from .metrics import get_registry

__all__ = ["TimeSeries"]


class TimeSeries:
    """Bounded per-metric sample rings + windowed delta queries."""

    def __init__(self, registry=None, capacity=1024):
        if capacity < 2:
            raise ValueError("capacity must be >= 2 (windows need a "
                             "baseline sample and a newest sample)")
        self.registry = registry        # None = the process registry
        self.capacity = int(capacity)
        self._lock = threading.RLock()
        self._rings = {}                # sample name -> deque[(ts, payload)]
        self._kinds = {}                # sample name -> metric kind
        self._buckets = {}              # sample name -> histogram edges
        self.samples_taken = 0          # sample() calls ever
        self.dropped = 0                # ring entries evicted (oldest)

    def _reg(self):
        return self.registry if self.registry is not None \
            else get_registry()

    # -- sampling ---------------------------------------------------------
    def sample(self, now=None):
        """Snapshot every registry child into its ring; returns the
        timestamp used. One registry-lock hold to copy, one own-lock
        hold to append — the serve loop calls this on a cadence, so the
        cost must stay far below a step."""
        reg = self._reg()
        now = time.monotonic() if now is None else float(now)
        rows = []
        with reg._lock:
            for name, fam in reg._metrics.items():
                for key, child in fam._children.items():
                    sname = fam._sample_name(key)
                    if fam.kind == "histogram":
                        rows.append((sname, "histogram", fam.buckets,
                                     (tuple(child.bucket_counts),
                                      child.sum, child.count)))
                    else:
                        rows.append((sname, fam.kind, None, child.value))
        self._append_rows(rows, now)
        return now

    def sample_snapshot(self, snapshot, now):
        """Append one ``registry.snapshot()`` DICT into the rings — the
        fleet-mirroring path (fleet_obs): a remote rank's exported
        snapshot replays into the same windowed machinery sample()
        feeds live, so delta/rate/quantile work identically on mirrored
        data. `now` is the REMOTE rank's monotonic clock (from its
        snapshot's clock stamp) — per-rank rings keep per-rank
        timebases, never mixed. Reserved meta entries ("_timeline")
        are skipped."""
        now = float(now)
        rows = []
        for name, fam in snapshot.items():
            kind = fam.get("kind")
            if name.startswith("_") or kind not in ("counter", "gauge",
                                                    "histogram"):
                continue
            labelnames = fam.get("labelnames") or []
            for ckey, child in (fam.get("children") or {}).items():
                if ckey:
                    kv = ",".join(f"{n}={v}" for n, v in
                                  zip(labelnames, ckey.split(",")))
                    sname = f"{name}{{{kv}}}"
                else:
                    sname = name
                if kind == "histogram":
                    rows.append((sname, kind, tuple(fam["buckets"]),
                                 (tuple(child["bucket_counts"]),
                                  float(child["sum"]),
                                  int(child["count"]))))
                else:
                    rows.append((sname, kind, None,
                                 float(child["value"])))
        self._append_rows(rows, now)
        return now

    def _append_rows(self, rows, now):
        with self._lock:
            self.samples_taken += 1
            for sname, kind, buckets, payload in rows:
                ring = self._rings.get(sname)
                if ring is None:
                    ring = self._rings[sname] = collections.deque(
                        maxlen=self.capacity)
                    self._kinds[sname] = kind
                    if buckets is not None:
                        self._buckets[sname] = tuple(buckets)
                if len(ring) == self.capacity:
                    self.dropped += 1
                ring.append((now, payload))

    # -- ring access ------------------------------------------------------
    def names(self):
        with self._lock:
            return sorted(self._rings)

    def kind(self, name):
        with self._lock:
            return self._kinds.get(name)

    def ring(self, name):
        """Snapshot of one metric's ring, oldest first."""
        with self._lock:
            return list(self._rings.get(name, ()))

    def clear(self):
        with self._lock:
            self._rings.clear()
            self._kinds.clear()
            self._buckets.clear()

    def _window_pair(self, name, window_s, now):
        """(baseline, newest) samples for a delta over the window ending
        at `now`: baseline is the LAST sample at or before the window's
        left edge (so observations that landed just inside the window
        are counted), falling back to the oldest retained sample when
        the ring does not reach back that far (a partial window — the
        span actually covered rides back to the caller). None when
        fewer than two samples exist or nothing precedes `now`."""
        with self._lock:
            ring = list(self._rings.get(name, ()))
        upto = [s for s in ring if s[0] <= now]
        if len(upto) < 2:
            return None
        newest = upto[-1]
        left = now - float(window_s)
        baseline = None
        for s in upto:
            if s[0] <= left:
                baseline = s
            else:
                break
        if baseline is None:
            baseline = upto[0]
        if baseline[0] >= newest[0]:
            return None
        return baseline, newest

    # -- counter / gauge windows -----------------------------------------
    def delta(self, name, window_s, now=None):
        """Increase of a counter (or net change of a gauge) over the
        window. None without enough samples or across a counter reset
        (a negative counter delta can only be a registry reset)."""
        now = time.monotonic() if now is None else float(now)
        pair = self._window_pair(name, window_s, now)
        if pair is None:
            return None
        (t0, v0), (t1, v1) = pair
        d = v1 - v0
        if self._kinds.get(name) == "counter" and d < 0:
            return None
        return d

    def rate(self, name, window_s, now=None):
        """Per-second increase over the window (None like delta)."""
        now = time.monotonic() if now is None else float(now)
        pair = self._window_pair(name, window_s, now)
        if pair is None:
            return None
        (t0, v0), (t1, v1) = pair
        d = v1 - v0
        if self._kinds.get(name) == "counter" and d < 0:
            return None
        return d / (t1 - t0)

    def gauge_stats(self, name, window_s, now=None):
        """{'min','max','mean','last','samples'} of the sampled values
        inside the window (None when the window holds no samples)."""
        now = time.monotonic() if now is None else float(now)
        left = now - float(window_s)
        with self._lock:
            ring = list(self._rings.get(name, ()))
        vals = [v for ts, v in ring if left <= ts <= now]
        if not vals:
            return None
        return {"min": min(vals), "max": max(vals),
                "mean": sum(vals) / len(vals), "last": vals[-1],
                "samples": len(vals)}

    # -- histogram windows ------------------------------------------------
    def hist_delta(self, name, window_s, now=None):
        """(bucket_count_deltas incl +Inf, sum_delta, count_delta) of a
        histogram over the window; None without enough samples or
        across a reset."""
        now = time.monotonic() if now is None else float(now)
        pair = self._window_pair(name, window_s, now)
        if pair is None:
            return None
        (_, (b0, s0, c0)), (_, (b1, s1, c1)) = pair
        if c1 < c0 or len(b0) != len(b1):
            return None                 # registry reset / rebucketing
        counts = [a - b for a, b in zip(b1, b0)]
        if any(c < 0 for c in counts):
            return None
        return counts, s1 - s0, c1 - c0

    def count(self, name, window_s, now=None):
        """Observations a histogram recorded inside the window."""
        d = self.hist_delta(name, window_s, now=now)
        return None if d is None else d[2]

    def quantile(self, name, q, window_s, now=None):
        """Delta-histogram quantile: the q-quantile of the observations
        recorded INSIDE the window — linear interpolation inside the
        crossing bucket, values past the last finite edge clamp to it
        (Histogram.quantile semantics on the windowed delta)."""
        if not 0 <= q <= 1:
            raise ValueError(f"quantile {q} outside [0, 1]")
        d = self.hist_delta(name, window_s, now=now)
        if d is None or d[2] == 0:
            return None
        counts, _, total = d
        buckets = self._buckets[name]
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if cum + c >= rank and c:
                lo = buckets[i - 1] if i > 0 else 0.0
                hi = buckets[i] if i < len(buckets) else buckets[-1]
                if hi <= lo:
                    return hi
                return lo + (hi - lo) * max(0.0, rank - cum) / c
            cum += c
        return buckets[-1]

    def fraction_over(self, name, threshold, window_s, now=None):
        """Share of the window's observations above `threshold` — the
        burn-rate numerator for latency objectives. Interpolates inside
        the bucket containing the threshold; the +Inf bucket counts
        fully above any threshold at or past the last finite edge
        (conservative: a threshold should sit inside the bucket range).
        None when the window recorded nothing."""
        threshold = float(threshold)
        d = self.hist_delta(name, window_s, now=now)
        if d is None or d[2] == 0:
            return None
        counts, _, total = d
        buckets = self._buckets[name]
        over = 0.0
        for i, c in enumerate(counts):
            if not c:
                continue
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i] if i < len(buckets) else None   # +Inf
            if hi is not None and hi <= threshold:
                continue                # bucket entirely at/below
            if lo >= threshold or hi is None:
                over += c               # entirely above (or +Inf)
            else:
                over += c * (hi - threshold) / (hi - lo)
        return over / total
