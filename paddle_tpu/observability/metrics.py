"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The serving/training nervous system the ROADMAP's "heavy traffic" regime
needs (reference: python/paddle/profiler + fleet metrics; bar: vLLM's
Prometheus surface). Design constraints, in order:

* **Host-side only, zero device round trips.** Every record call coerces
  its value through ``float()`` — a jax tracer fails that coercion, so a
  record call accidentally placed inside a jitted function raises at
  trace time with a pointed message instead of silently baking one stale
  value into the compiled program. graftlint GL105 enforces the same
  contract statically.
* **stdlib only.** This module must import in a bare CI container —
  before jax, before numpy — so the tier-0 gate can selfcheck it the way
  it selfchecks graftlint (tools/metrics_snapshot.py --selfcheck).
* **Lock-protected.** The serving engine, the comm-watchdog poller
  thread, and jax.monitoring compile callbacks all record concurrently;
  one process-wide mutex over tiny dict/float updates is far below the
  noise floor of a decode step.

Metric families follow the Prometheus data model: a family has a name, a
help string, and optional label names; ``family.labels(op="matmul")``
returns (creating on first use) the child that actually holds values.
Unlabeled families proxy straight to their single anonymous child, so
``registry.counter("steps_total").inc()`` just works.

Every counter/gauge mutation also appends a ``(ts_us, name, value)``
sample to a bounded timeline ring, which is what the chrome-trace
exporter turns into ``"ph": "C"`` counter events merged into the
profiler's host-range timeline.
"""
import bisect
import collections
import math
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "exponential_buckets", "DEFAULT_LATENCY_BUCKETS",
]


def exponential_buckets(start, factor, count):
    """`count` upper bounds growing geometrically from `start` (the +Inf
    bucket is implicit, never listed)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


# 1 ms .. ~131 s: covers TTFT on a real chip and on the CPU-interpret CI
DEFAULT_LATENCY_BUCKETS = exponential_buckets(0.001, 2.0, 18)


def _host_float(value, what):
    """Coerce to a host float; reject tracers (and anything else that is
    not a concrete scalar) loudly — this is the runtime half of the
    host-side-only contract (the static half is graftlint GL105)."""
    try:
        return float(value)
    except Exception as e:  # jax ConcretizationTypeError, TypeError, ...
        raise TypeError(
            f"observability: {what} needs a concrete host scalar, got "
            f"{type(value).__name__} — metrics are host-side only; a "
            "record call inside a jitted function would fire at trace "
            "time (once), not per step. Move it outside jit.") from e


class _Labeled:
    """Shared family plumbing: label handling + child management."""

    kind = "untyped"

    def __init__(self, registry, name, help, labelnames):
        self.registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames or ())
        self._children = {}          # label-value tuple -> child state

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name}: got labels {sorted(labelvalues)}, "
                f"declared {sorted(self.labelnames)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self.registry._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child(key)
        return child

    def _anon(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name} declares labels {self.labelnames}; "
                "use .labels(...)")
        return self.labels()

    def _sample_name(self, key):
        if not key:
            return self.name
        kv = ",".join(f"{n}={v}" for n, v in zip(self.labelnames, key))
        return f"{self.name}{{{kv}}}"


class _CounterChild:
    __slots__ = ("_family", "_key", "value")

    def __init__(self, family, key):
        self._family = family
        self._key = key
        self.value = 0.0

    def inc(self, amount=1):
        amount = _host_float(amount, f"counter {self._family.name} inc()")
        if amount < 0:
            raise ValueError(
                f"counter {self._family.name}: negative increment "
                f"{amount} (counters are monotonic; use a gauge)")
        fam = self._family
        with fam.registry._lock:
            self.value += amount
            fam.registry._sample(fam._sample_name(self._key), self.value)


class Counter(_Labeled):
    """Monotonic cumulative count (requests served, compiles, failures)."""

    kind = "counter"

    def _new_child(self, key):
        return _CounterChild(self, key)

    def inc(self, amount=1):
        self._anon().inc(amount)

    @property
    def value(self):
        return self._anon().value


class _GaugeChild:
    __slots__ = ("_family", "_key", "value")

    def __init__(self, family, key):
        self._family = family
        self._key = key
        self.value = 0.0

    def set(self, value):
        value = _host_float(value, f"gauge {self._family.name} set()")
        fam = self._family
        with fam.registry._lock:
            self.value = value
            fam.registry._sample(fam._sample_name(self._key), value)

    def inc(self, amount=1):
        amount = _host_float(amount, f"gauge {self._family.name} inc()")
        fam = self._family
        with fam.registry._lock:
            self.value += amount
            fam.registry._sample(fam._sample_name(self._key), self.value)

    def dec(self, amount=1):
        self.inc(-_host_float(amount, f"gauge {self._family.name} dec()"))

    def set_max(self, value):
        """High-water update: keep the max of current and `value`."""
        value = _host_float(value, f"gauge {self._family.name} set_max()")
        fam = self._family
        with fam.registry._lock:
            if value > self.value:
                self.value = value
                fam.registry._sample(fam._sample_name(self._key), value)


class Gauge(_Labeled):
    """Instantaneous level (free blocks, in-flight requests, tokens/s)."""

    kind = "gauge"

    def _new_child(self, key):
        return _GaugeChild(self, key)

    def set(self, value):
        self._anon().set(value)

    def inc(self, amount=1):
        self._anon().inc(amount)

    def dec(self, amount=1):
        self._anon().dec(amount)

    def set_max(self, value):
        self._anon().set_max(value)

    @property
    def value(self):
        return self._anon().value


class _HistogramChild:
    __slots__ = ("_family", "_key", "bucket_counts", "sum", "count")

    def __init__(self, family, key):
        self._family = family
        self._key = key
        self.bucket_counts = [0] * (len(family.buckets) + 1)  # + the +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        value = _host_float(value,
                            f"histogram {self._family.name} observe()")
        fam = self._family
        # `le` upper bounds are inclusive (Prometheus semantics)
        i = bisect.bisect_left(fam.buckets, value)
        with fam.registry._lock:
            self.bucket_counts[i] += 1
            self.sum += value
            self.count += 1
            fam.registry._sample(fam._sample_name(self._key), value)

    def quantile(self, q):
        """Estimate the q-quantile (0..1) by linear interpolation inside
        the bucket that crosses rank q*count — Prometheus
        histogram_quantile(). Values past the last finite edge clamp to
        it. None when empty."""
        if not 0 <= q <= 1:
            raise ValueError(f"quantile {q} outside [0, 1]")
        fam = self._family
        with fam.registry._lock:
            counts = list(self.bucket_counts)
            total = self.count
        if total == 0:
            return None
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if cum + c >= rank and c:
                lo = fam.buckets[i - 1] if i > 0 else 0.0
                hi = fam.buckets[i] if i < len(fam.buckets) \
                    else fam.buckets[-1]
                if hi <= lo:            # degenerate / +Inf bucket
                    return hi
                return lo + (hi - lo) * max(0.0, rank - cum) / c
            cum += c
        return fam.buckets[-1]


class Histogram(_Labeled):
    """Fixed-bucket cumulative-style histogram (latencies, step times)."""

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames, buckets=None):
        super().__init__(registry, name, help, labelnames)
        buckets = tuple(sorted(DEFAULT_LATENCY_BUCKETS if buckets is None
                               else buckets))
        if not buckets or any(not math.isfinite(b) for b in buckets):
            raise ValueError(
                f"histogram {name}: finite, non-empty bucket edges "
                "required (+Inf is implicit)")
        if len(set(buckets)) != len(buckets):
            raise ValueError(f"histogram {name}: duplicate bucket edges")
        self.buckets = buckets

    def _new_child(self, key):
        return _HistogramChild(self, key)

    def observe(self, value):
        self._anon().observe(value)

    def quantile(self, q):
        return self._anon().quantile(q)

    @property
    def count(self):
        return self._anon().count

    @property
    def sum(self):
        return self._anon().sum


class MetricsRegistry:
    """Name -> metric family, plus the bounded chrome-counter timeline."""

    def __init__(self, timeline_capacity=65536):
        self._lock = threading.RLock()
        self._metrics = {}
        self._samples = collections.deque(maxlen=timeline_capacity)
        self.timeline_enabled = True
        # samples the bounded ring evicted (oldest-first): a long
        # serving run's chrome timeline silently starts mid-flight
        # otherwise — the drop count makes the truncation visible
        # (snapshot()'s "_timeline" entry, serve_monitor's dashboard)
        self.timeline_dropped = 0

    # -- family constructors (get-or-create, type-checked) ---------------
    def _family(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(self, name, help,
                                              labels, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            elif labels and tuple(labels) != m.labelnames:
                raise ValueError(
                    f"metric {name} already registered with labels "
                    f"{m.labelnames}, requested {tuple(labels)}")
        return m

    def counter(self, name, help="", labels=()):
        return self._family(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._family(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(), buckets=None):
        return self._family(Histogram, name, help, labels,
                            buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    # -- timeline ---------------------------------------------------------
    def _sample(self, name, value):
        # caller holds self._lock. perf_counter, NOT time.time(): the
        # profiler stamps its host ranges with perf_counter microseconds,
        # and these samples merge into that chrome stream — a different
        # timebase would land the counter track nowhere near the ranges.
        if self.timeline_enabled:
            if len(self._samples) == self._samples.maxlen:
                self.timeline_dropped += 1      # deque evicts the oldest
            self._samples.append((time.perf_counter() * 1e6, name, value))

    def timeline(self):
        with self._lock:
            return list(self._samples)

    def timeline_stats(self):
        """{'samples','capacity','dropped'}: how much of the recorded
        history the bounded ring still holds — `dropped` > 0 means a
        chrome export of this timeline is truncated at the front."""
        with self._lock:
            return {"samples": len(self._samples),
                    "capacity": self._samples.maxlen,
                    "dropped": self.timeline_dropped}

    # -- snapshot ---------------------------------------------------------
    def snapshot(self):
        """Plain-dict dump of every family and child (json-friendly)."""
        out = {}
        with self._lock:
            for name, m in self._metrics.items():
                entry = {"kind": m.kind, "help": m.help,
                         "labelnames": list(m.labelnames)}
                if m.kind == "histogram":
                    entry["buckets"] = list(m.buckets)
                children = {}
                for key, child in m._children.items():
                    cname = ",".join(key) if key else ""
                    if m.kind == "histogram":
                        children[cname] = {
                            "bucket_counts": list(child.bucket_counts),
                            "sum": child.sum, "count": child.count}
                    else:
                        children[cname] = {"value": child.value}
                entry["children"] = children
                out[name] = entry
            # ring-truncation marker (never a real family: names with a
            # leading underscore are reserved). "kind"/"children" keep
            # the family shape so generic consumers iterate safely.
            out["_timeline"] = {"kind": "meta", "help": "", "children": {},
                                "labelnames": [],
                                **self.timeline_stats()}
        return out

    def reset(self):
        """Drop every metric and timeline sample (tests). Instrumented
        code must re-fetch families through the registry on each record —
        holding a family handle across reset() orphans it."""
        with self._lock:
            self._metrics.clear()
            self._samples.clear()
            self.timeline_dropped = 0


_registry = MetricsRegistry()


def get_registry():
    """The process-wide registry every subsystem records into."""
    return _registry
