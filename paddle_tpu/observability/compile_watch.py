"""Compile watch: XLA recompiles as first-class metrics.

Recompiles are the silent killer of the serving contract ("bucketed
work-list, no recompiles past the first few buckets" —
incubate/nn/continuous_batching.py): a shape leak turns every admission
into a multi-second XLA compile and the only symptom is a mysteriously
slow step. jax already announces every trace/lower/compile through
``jax.monitoring``; this module turns those announcements into registry
metrics:

* ``jax_compiles_total{stage=...}`` — counter per pipeline stage
  (trace / lower / backend_compile), labeled ``fn`` when the running jax
  passes ``fun_name`` metadata (newer jax; older versions label
  ``unknown`` — graceful degradation, never a crash).
* ``jax_compile_seconds{stage=...}`` — wall-time histogram per stage.
* ``jax_cache_events_total{event=...}`` — compilation-cache hit/miss
  counters.

``install()`` is idempotent and returns False (a no-op) on jax builds
without ``jax.monitoring`` — the listener API only exists from jax
0.4.x on, and this package must degrade to nothing, not an ImportError.
"""
from .metrics import get_registry

__all__ = ["install", "installed", "COMPILE_STAGES"]

# suffix of the jax.monitoring duration event -> short stage label
COMPILE_STAGES = {
    "jaxpr_trace_duration": "trace",
    "jaxpr_to_mlir_module_duration": "lower",
    "backend_compile_duration": "backend_compile",
}

# compile wall-times span ~100 us (cache hit path) to minutes (big TPU
# programs): wider-than-latency buckets
_COMPILE_BUCKETS = tuple(1e-4 * 4.0 ** i for i in range(10))

_installed = False


def _stage_of(event):
    for suffix, stage in COMPILE_STAGES.items():
        if event.endswith(suffix):
            return stage
    return None


def _on_duration(event, duration, **kwargs):
    stage = _stage_of(event)
    if stage is None:
        return
    reg = get_registry()
    fn = str(kwargs.get("fun_name", "unknown"))
    reg.counter("jax_compiles_total",
                help="jax trace/lower/compile invocations",
                labels=("stage", "fn")).labels(stage=stage, fn=fn).inc()
    reg.histogram("jax_compile_seconds",
                  help="jax trace/lower/compile wall time",
                  labels=("stage",),
                  buckets=_COMPILE_BUCKETS).labels(stage=stage).observe(
                      duration)


def _on_event(event, **kwargs):
    if not event.startswith("/jax/compilation_cache/"):
        return
    get_registry().counter(
        "jax_cache_events_total",
        help="jax compilation-cache events",
        labels=("event",)).labels(event=event.rsplit("/", 1)[-1]).inc()


def install():
    """Register the jax.monitoring listeners once. Returns True when
    listening, False when this jax has no monitoring API (no-op)."""
    global _installed
    if _installed:
        return True
    try:
        from jax import monitoring
    except Exception:
        return False
    if not (hasattr(monitoring, "register_event_duration_secs_listener")
            and hasattr(monitoring, "register_event_listener")):
        return False
    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)
    _installed = True
    return True


def installed():
    return _installed
