"""Standard instrument set + op-dispatch counting.

The well-known metric families every instrumented surface shares live
here as accessor functions, not cached handles: each call re-fetches the
family through the registry (two dict lookups under the lock — noise
next to a device step), so ``registry.reset()`` in a test can never
leave an instrumented module holding an orphaned family.

``watch_ops()`` hooks the eager dispatch choke point
(core/dispatch.py): every ``apply_op`` already fans out to the
registered op listeners — under tracing too — so one listener gives
op-call counters for free, composing with the profiler's op tracer
instead of fighting it for the single ``set_op_tracer`` slot.
"""
from .metrics import DEFAULT_LATENCY_BUCKETS, get_registry

__all__ = [
    "watch_ops", "serve_ttft", "serve_tpot", "serve_queue_wait",
    "serve_step_seconds", "dispatch_seconds", "serve_tokens_total",
    "serve_requests_total",
    "serve_inflight", "serve_queue_depth", "serve_tokens_per_s",
    "kv_blocks_free", "kv_blocks_used", "kv_blocks_high_water",
    "kv_alloc_failures", "serve_bucket_recompiles",
    "spec_draft_tokens", "spec_accepted_tokens", "spec_accept_len",
    "serve_effective_tokens_per_step", "serve_prefill_chunk",
    "prefix_cache_hits", "prefix_cache_misses", "prefix_cache_evictions",
    "prefix_cache_cow", "kv_blocks_shared", "kv_blocks_prefix_resident",
    "serve_preemptions", "serve_cancelled", "serve_shed",
    "serve_deadline_exceeded", "serve_failed", "serve_rejected",
    "gateway_request_seconds", "gateway_stream_seconds",
    "gateway_responses", "gateway_live_connections",
    "gateway_live_streams", "gateway_sse_pending_events",
    "gateway_sse_events", "gateway_health_transitions",
    "routed_requests", "router_affinity_hits", "router_affinity_misses",
    "router_resubmits", "router_replica_inflight",
    "router_replicas_live",
    "train_step_seconds", "train_tokens_total", "train_steps_total",
    "train_tokens_per_s", "train_host_seconds",
    "autotune_trials", "autotune_cache_hits", "autotune_cache_misses",
    "autotune_winner",
    "serve_host_phase_seconds", "serve_work_segments",
    "serve_work_assemblies", "serve_input_copy_bytes",
]


# -- serving (continuous-batching engine) --------------------------------

def serve_ttft():
    return get_registry().histogram(
        "serve_ttft_seconds",
        help="submit -> first generated token, per request")


def serve_tpot():
    return get_registry().histogram(
        "serve_time_per_output_token_seconds",
        help="interval between consecutive generated tokens, per slot")


def serve_queue_wait():
    return get_registry().histogram(
        "serve_queue_wait_seconds",
        help="submit -> admission into a batch slot, per request")


def serve_step_seconds():
    return get_registry().histogram(
        "serve_step_seconds",
        help="one scheduler tick + compiled decode step (host wall)")


def serve_host_phase_seconds():
    return get_registry().histogram(
        "serve_host_phase_seconds",
        help="host side of one serving step, split by phase: schedule "
             "(retire/admit/chunk grants/grow), build (slab/sel/work-"
             "list assembly), dispatch (compiled-step enqueue), overlap "
             "(token-independent host work hidden under device "
             "execution), fetch (block on sampled tokens), commit "
             "(accept/rewind/emission bookkeeping)",
        labels=("phase",))     # bounded: the six phases above


def serve_work_segments():
    return get_registry().counter(
        "serve_work_segments_total",
        help="per-slot ragged work-list segments per step, by outcome: "
             "reused (buffer entry already correct) vs rebuilt (slot "
             "dirtied by admit/grow/COW/rewind/preempt/retire)",
        labels=("event",))     # bounded: reused | rebuilt


def serve_work_assemblies():
    return get_registry().counter(
        "serve_work_assemblies_total",
        help="work-list assemblies by mode: incremental (layout + "
             "bucket unchanged, only dirty segments rewritten) vs full "
             "(re-laid out into the bucket buffer)",
        labels=("mode",))      # bounded: incremental | full


def serve_input_copy_bytes():
    return get_registry().counter(
        "serve_step_input_copy_bytes_total",
        help="bytes freshly allocated/copied for compiled-step inputs "
             "(slab, sel, work list, q/attn lens) — 0 in steady state "
             "on the host fast path, nonzero only on the legacy "
             "per-step-rebuild path")


def dispatch_seconds():
    return get_registry().histogram(
        "dispatch_seconds",
        help="compiled-program dispatch (trace/lower/compile on a fresh "
             "bucket + enqueue, NOT device completion), per program",
        labels=("program",))


def serve_tokens_total():
    return get_registry().counter(
        "serve_tokens_total", help="generated tokens")


def serve_requests_total():
    return get_registry().counter(
        "serve_requests_finished_total", help="requests retired")


def serve_inflight():
    return get_registry().gauge(
        "serve_inflight_requests", help="occupied batch slots")


def serve_queue_depth():
    return get_registry().gauge(
        "serve_queue_depth", help="submitted, not yet admitted")


def serve_tokens_per_s():
    return get_registry().gauge(
        "serve_tokens_per_s",
        help="tokens emitted by the last step / its host wall time")


def kv_blocks_free():
    return get_registry().gauge(
        "kv_blocks_free", help="allocatable cache blocks on the free list")


def kv_blocks_used():
    return get_registry().gauge(
        "kv_blocks_used", help="cache blocks held by in-flight requests")


def kv_blocks_high_water():
    return get_registry().gauge(
        "kv_blocks_high_water",
        help="max cache blocks ever simultaneously in use")


def kv_alloc_failures():
    return get_registry().counter(
        "kv_alloc_failures_total",
        help="BlockAllocator.alloc() calls that found an empty free list")


def serve_bucket_recompiles():
    return get_registry().counter(
        "serve_bucket_recompiles_total",
        help="first sighting of a padded work-list length (keys one "
             "XLA compile of the decode step)", labels=("bucket",))


# -- automatic prefix caching (content-addressed paged-KV sharing) -------

def prefix_cache_hits():
    return get_registry().counter(
        "serve_prefix_cache_hits_total",
        help="full prompt blocks mapped from the shared prefix index "
             "instead of prefilled (each hit skips block_size tokens "
             "of prefill compute)")


def prefix_cache_misses():
    return get_registry().counter(
        "serve_prefix_cache_misses_total",
        help="full prompt blocks probed against the prefix index and "
             "not found (counted once per prompt position per request)")


def prefix_cache_evictions():
    return get_registry().counter(
        "serve_prefix_cache_evictions_total",
        help="pooled prefix blocks reclaimed (LRU-oldest first) because "
             "the free list could not cover an allocation")


def prefix_cache_cow():
    return get_registry().counter(
        "serve_prefix_cache_cow_copies_total",
        help="copy-on-write block duplications: a request appended into "
             "a physical block other requests still read")


def kv_blocks_shared():
    return get_registry().gauge(
        "kv_blocks_shared",
        help="physical cache blocks referenced by more than one request")


def kv_blocks_prefix_resident():
    return get_registry().gauge(
        "kv_blocks_prefix_resident",
        help="physical blocks resident in the prefix index (held by "
             "requests or parked in the LRU reuse pool)")


# -- serving resilience (preemption / cancellation / shedding) -----------
# reason labels are drawn from small FIXED sets (the engine spells them
# as literals), never from request ids or prompt content — the GL112
# bounded-cardinality contract

def serve_preemptions():
    return get_registry().counter(
        "serve_preemptions_total",
        help="requests preempted to blocks (KV freed, request re-queued "
             "for prefix-cache-assisted re-prefill)", labels=("reason",))


def serve_cancelled():
    return get_registry().counter(
        "serve_requests_cancelled_total",
        help="requests retired mid-flight (or dequeued) by cancel()")


def serve_shed():
    return get_registry().counter(
        "serve_requests_shed_total",
        help="queued low-priority requests shed by pressure-aware "
             "admission before the KV pool exhausted", labels=("reason",))


def serve_deadline_exceeded():
    return get_registry().counter(
        "serve_requests_deadline_exceeded_total",
        help="requests retired at their step/wall deadline with a "
             "partial generation")


def serve_failed():
    return get_registry().counter(
        "serve_requests_failed_total",
        help="per-request failures that used to be engine crashes "
             "(kv_alloc_failure with no preemptible victim)",
        labels=("reason",))


def serve_rejected():
    return get_registry().counter(
        "serve_requests_rejected_total",
        help="requests rejected at submit() for unsupported config "
             "combos (structured, instead of a mid-step raise)",
        labels=("reason",))


# -- serving gateway (HTTP/SSE front door) -------------------------------
# every label value below comes from a small FIXED set the gateway
# spells as literals (route names, SSE event types, health states, HTTP
# codes the gateway itself emits) — the GL112 bounded-cardinality
# contract; per-request identity lives in spans, never in labels

def gateway_request_seconds():
    return get_registry().histogram(
        "gateway_request_seconds",
        help="HTTP request handling wall time (headers-in to "
             "response-flushed; SSE streams count separately)",
        labels=("route",))


def gateway_stream_seconds():
    return get_registry().histogram(
        "gateway_stream_seconds",
        help="SSE stream lifetime: headers sent -> terminal event "
             "flushed (or client gone)")


def gateway_responses():
    return get_registry().counter(
        "gateway_responses_total",
        help="HTTP responses by route and status code (codes are the "
             "gateway's own fixed set)", labels=("route", "code"))


def gateway_live_connections():
    return get_registry().gauge(
        "gateway_live_connections",
        help="TCP connections currently open against the gateway")


def gateway_live_streams():
    return get_registry().gauge(
        "gateway_live_streams",
        help="SSE token streams currently open")


def gateway_sse_pending_events():
    return get_registry().gauge(
        "gateway_sse_pending_events",
        help="SSE events queued for delivery but not yet written — "
             "sustained growth means a slow client (backpressure)")


def gateway_sse_events():
    return get_registry().counter(
        "gateway_sse_events_total",
        help="SSE events written, by event type (fixed set: "
             "accepted/token/end)", labels=("event",))


def gateway_health_transitions():
    return get_registry().counter(
        "gateway_health_transitions_total",
        help="/healthz state changes (ok <-> degraded)",
        labels=("to",))


# -- multi-replica router (data-parallel engine pool) --------------------
# `replica` is world-bounded (one value per pool slot, like `device`)
# and `policy` is the router's fixed literal set — GL112-safe.

def routed_requests():
    return get_registry().counter(
        "routed_requests_total",
        help="requests routed to a replica, by policy and pool slot",
        labels=("policy", "replica"))


def router_affinity_hits():
    return get_registry().counter(
        "router_affinity_hits_total",
        help="prefix-affinity routes that matched a replica's "
             "published prefix index (>= 1 leading block mapped free)")


def router_affinity_misses():
    return get_registry().counter(
        "router_affinity_misses_total",
        help="prefix-affinity routes that fell back to least-loaded "
             "(no index match, or the imbalance cap vetoed the match)")


def router_resubmits():
    return get_registry().counter(
        "router_resubmits_total",
        help="queued requests resubmitted to a survivor after their "
             "replica's step() crashed, by the SURVIVOR's pool slot",
        labels=("replica",))


def router_replica_inflight():
    return get_registry().gauge(
        "router_replica_inflight",
        help="requests the router currently has routed to each "
             "replica (submit -> terminal, queued + active)",
        labels=("replica",))


def router_replicas_live():
    return get_registry().gauge(
        "router_replicas_live",
        help="replicas currently accepting routes (pool size minus "
             "drained)")


# -- speculative decode (prompt-lookup drafts + budgeted verify) ---------

def spec_draft_tokens():
    return get_registry().counter(
        "spec_draft_tokens_total",
        help="prompt-lookup draft tokens handed to the verifier")


def spec_accepted_tokens():
    return get_registry().counter(
        "spec_accepted_tokens_total",
        help="draft tokens accepted by greedy verification "
             "(rate vs spec_draft_tokens_total = acceptance rate)")


def spec_accept_len(max_len=8):
    # acceptance lengths are small ints (0..spec_k); linear buckets so
    # the histogram reads as a per-length distribution, not latency.
    # The serving engine pins the bucket range at construction by
    # calling this with its spec_k (buckets bind on FIRST creation;
    # later calls return the existing family) — a spec_k=16 engine gets
    # distinguishable 9..16 lengths instead of one +Inf blob
    return get_registry().histogram(
        "serve_spec_accept_len",
        help="accepted-prefix length per verified draft span",
        buckets=tuple(float(i) for i in range(int(max_len) + 1)))


def serve_effective_tokens_per_step():
    return get_registry().gauge(
        "serve_effective_tokens_per_step",
        help="tokens emitted by the last compiled step (speculation "
             "pushes this above the decode-slot count)")


def serve_prefill_chunk():
    return get_registry().gauge(
        "serve_prefill_chunk",
        help="current prefill chunk size (the TPOT-SLO controller "
             "shrinks it one pow2 bucket when decode latency degrades)")


# -- tensor-parallel serving (kv-head-sharded paged cache) ---------------

def serve_tp_degree():
    return get_registry().gauge(
        "serve_tp_degree",
        help="tensor-parallel width of the serving engine's device "
             "mesh (1 = single-chip)")


def kv_device_bytes_used():
    # per-device children are bounded by the mesh topology (tp <=
    # device count), not by traffic — the same contract as the
    # shard_bytes/hbm_device_* families in observability/memory.py
    return get_registry().gauge(
        "kv_device_bytes_used",
        help="paged-KV cache bytes held by in-flight requests on each "
             "device's kv-head shard (blocks_used x per-device block "
             "bytes; drops by the TP factor vs single-chip)",
        labels=("device",))


def kv_device_bytes_high_water():
    return get_registry().gauge(
        "kv_device_bytes_high_water",
        help="peak per-device paged-KV bytes ever in use (the serve_tp "
             "gate asserts 1/tp of the single-chip figure)",
        labels=("device",))


# -- training (pretrain loop) --------------------------------------------

def train_step_seconds():
    return get_registry().histogram(
        "train_step_seconds",
        help="pretrain step dispatch wall time (async dispatch: excludes "
             "device completion unless the caller blocks)")


def train_tokens_total():
    return get_registry().counter(
        "train_tokens_total", help="tokens entering the train step")


def train_steps_total():
    return get_registry().counter(
        "train_steps_total", help="train steps dispatched")


def train_tokens_per_s():
    return get_registry().gauge(
        "train_tokens_per_s",
        help="batch tokens / host wall of the last dispatched step")


# -- training health (step-phase breakdown) ------------------------------
# the step splits into data-wait (loader) vs host (python between
# dispatches) vs dispatch (train_step_seconds above). The data-pipeline
# families (train_data_wait_seconds, train_data_batches_total,
# train_data_queue_depth, train_data_stalls_total) and the per-layer-
# group telemetry gauges + breach counter are OWNED by
# train_health.py — it needs per-test registries, which these
# process-registry accessors can't take

def train_host_seconds():
    return get_registry().histogram(
        "train_host_seconds",
        help="host wall between dispatches not spent waiting on data "
             "(optimizer bookkeeping, logging, sharding the batch)")


# -- kernel autotuning (ops/pallas/autotune.py) --------------------------

def autotune_trials():
    # the kernel label is the family prefix of the tune key (flash_bshd,
    # ragged_paged_attention, ...), never the shape-bearing key itself —
    # a handful of Pallas kernels exist, so the child set stays bounded
    return get_registry().counter(
        "autotune_trials_total",
        help="candidate kernel configs timed (device) or scored "
             "(analytic model) by the autotuner",
        labels=("kernel",))


def autotune_cache_hits():
    return get_registry().counter(
        "autotune_cache_hits_total",
        help="autotune winner-cache lookups that found an entry "
             "(engine-construction time only: the zero-per-step-cost "
             "contract)")


def autotune_cache_misses():
    return get_registry().counter(
        "autotune_cache_misses_total",
        help="autotune winner-cache lookups that fell back to defaults")


def autotune_winner():
    return get_registry().gauge(
        "autotune_winner_config",
        help="last swept winner's tunable values, one child per "
             "(kernel, param): pack / prefill_chunk / buffer_depth",
        labels=("kernel", "param"))


# -- op dispatch ----------------------------------------------------------

_op_listener = None


def watch_ops(enable=True):
    """Count every eager op dispatch into ``op_calls_total{op=...}``.

    Rides core.dispatch's op-listener fan-out (fires under tracing too,
    so traced regions count their trace-time dispatches exactly once —
    which is what you want to see: a hot per-step count that keeps
    growing means ops are NOT getting fused into a jitted step)."""
    global _op_listener
    from ..core import dispatch
    if enable:
        if _op_listener is not None:
            return
        def _count(name, n_inputs, outs):
            get_registry().counter(
                "op_calls_total", help="eager/traced op dispatches",
                labels=("op",)).labels(op=name).inc()
        dispatch.add_op_listener(_count)
        _op_listener = _count
    elif _op_listener is not None:
        dispatch.remove_op_listener(_op_listener)
        _op_listener = None
